"""File readers: binary files, images, CSV — the ingestion layer (L2).

Re-expression of the reference's readers
(``readers/src/main/scala/{Readers,BinaryFileReader,ImageReader}.scala``):

- ``read_binary_files(path, recursive, sample_ratio, inspect_zip, seed)``:
  recursive directory walk (the hadoopConf RecursiveFlag,
  ``core/hadoop/src/main/scala/HadoopUtils.scala:156-176``), seeded
  fractional file sampling (SamplePathFilter ``:80-154``), and zip-entry
  streaming with the same seeded sampling (FileUtilities ``ZipIterator``
  ``:93-138``);
- ``read_images``: binary read + decode; undecodable files are dropped as in
  the reference (``ImageReader.scala:55-59``) with the drop count recorded in
  the frame's column metadata so it is observable;
- partitioning: files are split round-robin into ``num_partitions``
  partitions for downstream parallel decode.
"""
from __future__ import annotations

import csv as _csv
import io
import os
import random
import zipfile
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.schema import ColumnSchema, DType, ImageValue, Schema
from mmlspark_tpu.io.codecs import decode_image
from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.reliability.faults import fault_site


def _list_files(path: str, recursive: bool) -> List[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    if recursive:
        for root, _dirs, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in sorted(files))
    else:
        for f in sorted(os.listdir(path)):
            full = os.path.join(path, f)
            if os.path.isfile(full):
                out.append(full)
    return sorted(out)


def _sample(items: List, ratio: float, seed: int) -> List:
    """Seeded fractional sampling (reference SamplePathFilter semantics:
    independent coin flip per item)."""
    if ratio >= 1.0:
        return items
    rng = random.Random(seed)
    return [x for x in items if rng.random() < ratio]


def _process_slice(items: List, process_shard: bool) -> List:
    """This process's contiguous slice of a sorted work list (multi-process
    ingestion: every host lists the same files, reads only its share —
    the reader-level face of ``Frame.process_shard``)."""
    if not process_shard:
        return items
    import jax
    i, p = jax.process_index(), jax.process_count()
    bounds = np.linspace(0, len(items), p + 1).astype(int)
    return items[bounds[i]:bounds[i + 1]]


def list_binary_entries(path: str, recursive: bool = False,
                        sample_ratio: float = 1.0, inspect_zip: bool = True,
                        seed: int = 0,
                        process_shard: bool = False
                        ) -> List[Tuple[str, Optional[str]]]:
    """The deterministic entry LISTING under every binary reader: a list of
    ``(file_path, zip_entry_name_or_None)`` after the recursive walk, the
    seeded fractional sample, the zip-entry expansion, and the per-process
    contiguous slice. Pure metadata — no payload is read — so it doubles
    as the shard/cursor space for the streaming pipeline's ``FileSource``
    (``data/pipeline.py``): entry ``i`` here is record ``i`` there, and in
    ``iter_binary_entries``/``read_binary_files``.
    """
    if not 0.0 < sample_ratio <= 1.0:
        raise ValueError(f"sample_ratio must be in (0, 1], got {sample_ratio}")
    all_files = _list_files(path, recursive)
    # Zips are exempt from file-level sampling when inspected — their ENTRIES
    # are sampled instead (reference SamplePathFilter, HadoopUtils.scala:104:
    # `isZipFile(path) && inspectZip || random < sampleRatio`).
    zips = {f for f in all_files
            if inspect_zip and f.endswith(".zip") and zipfile.is_zipfile(f)}
    files = _process_slice(
        sorted(_sample([f for f in all_files if f not in zips],
                       sample_ratio, seed) + list(zips)), process_shard)
    entries: List[Tuple[str, Optional[str]]] = []
    for f in files:
        if f in zips:
            with zipfile.ZipFile(f) as z:
                names = [n for n in sorted(z.namelist())
                         if not n.endswith("/")]
                # zip entries are themselves subject to the sample ratio
                # (reference ZipIterator seeded sampling)
                entries.extend((f, n) for n in _sample(names, sample_ratio,
                                                       seed))
        else:
            entries.append((f, None))
    return entries


def iter_binary_entries(path: str, recursive: bool = False,
                        sample_ratio: float = 1.0, inspect_zip: bool = True,
                        seed: int = 0, process_shard: bool = False):
    """Lazily yield ``(path, bytes)`` one entry at a time.

    The streaming core under both the eager Frame readers and the chunked
    ``stream_*`` APIs: only the file LISTING is materialized up front; each
    blob is read (and each zip opened) as the consumer pulls it, so a
    terabyte image corpus streams through O(one file) of memory.

    ``process_shard=True`` keeps only this process's contiguous slice of
    the sorted file list (a zip counts as one file; its entries stay
    together) — per-host ingestion for multi-process training.
    """
    entries = list_binary_entries(path, recursive, sample_ratio, inspect_zip,
                                  seed, process_shard)
    zf_path: Optional[str] = None
    zf: Optional[zipfile.ZipFile] = None
    try:
        for f, inner in entries:
            if inner is None:
                with open(f, "rb") as fh:
                    yield f, fault_site("readers.read", payload=fh.read())
            else:
                if zf_path != f:  # entries of one zip are contiguous
                    if zf is not None:
                        zf.close()
                    zf_path, zf = f, zipfile.ZipFile(f)
                yield f"{f}/{inner}", fault_site("readers.read",
                                                 payload=zf.read(inner))
    finally:
        if zf is not None:
            zf.close()


def stream_binary_files(path: str, recursive: bool = False,
                        sample_ratio: float = 1.0, inspect_zip: bool = True,
                        seed: int = 0, chunk_rows: int = 256):
    """Yield host-batch dicts ``{"path", "bytes"}`` of <= chunk_rows entries.

    The lazy counterpart of :func:`read_binary_files` for corpora that do
    not fit in memory — chunks feed DevicePrefetcher / DistributedTrainer
    directly, replacing the reference's write-to-shared-FS hand-off
    (``CNTKLearner.scala:93-125``) with bounded-memory streaming.
    """
    paths: List[str] = []
    blobs: List[bytes] = []
    for p, b in iter_binary_entries(path, recursive, sample_ratio,
                                    inspect_zip, seed):
        paths.append(p)
        blobs.append(b)
        if len(paths) >= chunk_rows:
            yield {"path": _object_array(paths), "bytes": _object_array(blobs)}
            paths, blobs = [], []
    if paths:
        yield {"path": _object_array(paths), "bytes": _object_array(blobs)}


def stream_images(path: str, recursive: bool = False,
                  sample_ratio: float = 1.0, inspect_zip: bool = True,
                  seed: int = 0, chunk_rows: int = 256,
                  decode_threads: int = 8):
    """Yield ``{"path", "image"}`` chunks of decoded images, lazily.

    Decode runs per chunk through the native threaded pool; undecodable
    entries are dropped within their chunk (``ImageReader.scala:55-59``
    semantics). Memory high-water mark is one chunk of decoded images.
    """
    for chunk in stream_binary_files(path, recursive, sample_ratio,
                                     inspect_zip, seed, chunk_rows):
        decoded = _decode_blobs(list(chunk["bytes"]),
                                n_threads=decode_threads)
        images, keep = [], []
        for pth, arr in zip(chunk["path"], decoded):
            if arr is not None:
                images.append(ImageValue(path=pth, data=arr))
                keep.append(pth)
        if len(images) < len(decoded):
            obsmetrics.counter("data.decode_dropped").inc(
                len(decoded) - len(images))
        if images:
            yield {"path": _object_array(keep), "image": _object_array(images)}


def _object_array(values: Sequence) -> np.ndarray:
    arr = np.empty(len(values), dtype=np.object_)
    for i, v in enumerate(values):
        arr[i] = v
    return arr


def read_binary_files(path: str, recursive: bool = False,
                      sample_ratio: float = 1.0, inspect_zip: bool = True,
                      seed: int = 0, num_partitions: int = 1,
                      process_shard: bool = False) -> Frame:
    """Frame with (path, bytes) columns — reference BinaryFileSchema.
    ``process_shard=True``: this host reads only its slice of the file list."""
    paths: List[str] = []
    blobs: List[bytes] = []
    for p, b in iter_binary_entries(path, recursive, sample_ratio,
                                    inspect_zip, seed, process_shard):
        paths.append(p)
        blobs.append(b)
    frame = Frame.from_dict({"path": paths, "bytes": blobs},
                            schema=Schema([
                                ColumnSchema("path", DType.STRING),
                                ColumnSchema("bytes", DType.BINARY)]))
    return frame.repartition(num_partitions) if num_partitions > 1 else frame


def _decode_blobs(blobs: Sequence[bytes],
                  n_threads: int = 8) -> List[Optional[np.ndarray]]:
    """Batch decode: native threaded pool (JPEG/PNG) with per-blob python
    fallback for the formats/failures it does not cover (e.g. BMP)."""
    try:
        from mmlspark_tpu.utils.native_loader import (
            native_available, native_decode_batch)
        native = native_available()
    except Exception:
        native = False
    results: List[Optional[np.ndarray]] = [None] * len(blobs)
    if native:
        results = native_decode_batch(list(blobs), n_threads=n_threads)
    for i, r in enumerate(results):
        if r is None:
            results[i] = decode_image(blobs[i])
    return results


def read_images(path: str, recursive: bool = False, sample_ratio: float = 1.0,
                inspect_zip: bool = True, seed: int = 0,
                num_partitions: int = 1, decode_threads: int = 8,
                process_shard: bool = False) -> Frame:
    """Frame with one IMAGE column named 'image'; undecodable files dropped.
    ``process_shard=True``: this host reads/decodes only its file slice."""
    binary = read_binary_files(path, recursive, sample_ratio, inspect_zip,
                               seed, num_partitions, process_shard)
    dropped = 0
    parts = []
    for p in binary.partitions:
        images, keep_paths = [], []
        decoded = _decode_blobs(list(p["bytes"]), n_threads=decode_threads)
        for pth, arr in zip(p["path"], decoded):
            if arr is None:
                dropped += 1
                continue
            images.append(ImageValue(path=pth, data=arr))
            keep_paths.append(pth)
        parts.append({"path": _object_array(keep_paths),
                      "image": _object_array(images)})
    if dropped:
        # drops are rare by construction: unconditional cold counter, so
        # the loss shows in run reports even with hot-path metrics off
        obsmetrics.counter("data.decode_dropped").inc(dropped)
    schema = Schema([
        ColumnSchema("path", DType.STRING),
        ColumnSchema("image", DType.IMAGE,
                     metadata={"dropped_undecodable": dropped}),
    ])
    return Frame(schema, parts)


def read_csv(path: str, header: bool = True, num_partitions: int = 1,
             infer_types: bool = True, process_shard: bool = False) -> Frame:
    """Small CSV reader for the tabular paths (the reference leaned on
    spark.read.csv; this covers the benchmark/AutoML datasets).
    ``process_shard=True``: keep only this host's contiguous row slice
    (single-file format — every host parses, then keeps its share)."""
    with open(path, newline="") as f:
        rows = list(_csv.reader(f))
    if not rows:
        raise ValueError(f"empty csv: {path}")
    names = rows[0] if header else [f"c{i}" for i in range(len(rows[0]))]
    data_rows = rows[1:] if header else rows
    cols: dict = {n: [] for n in names}
    for r in data_rows:
        for n, v in zip(names, r):
            cols[n].append(v)
    if infer_types:
        # Types are inferred from the FULL row set BEFORE the per-process
        # slice: every host parses the whole file anyway, and slicing first
        # would let hosts disagree on a column's dtype (int-looking first
        # half vs fractional second half) — per-host schema divergence in
        # the SPMD fit this flag exists for.
        for n, vals in cols.items():
            cols[n] = _infer_csv_column(vals)
    if process_shard:
        cols = {n: _process_slice(vals, True) for n, vals in cols.items()}
    return Frame.from_dict(cols, num_partitions=num_partitions)


def read_parquet(path: str, columns: Optional[Sequence[str]] = None,
                 num_partitions: int = 1,
                 process_shard: bool = False) -> Frame:
    """Parquet ingestion — Spark's native storage format, so this is the
    highest-parity on-ramp for data produced by the reference's world
    (``spark.read.parquet``). ``path`` is a file or a directory of part
    files; ``process_shard=True`` keeps this host's slice of the sorted
    part-file list (multi-file datasets) for multi-process training.

    Column mapping: numeric/bool -> numeric columns; string -> STRING;
    binary -> BINARY; list<number> with uniform lengths -> VECTOR;
    list<string> -> TOKENS.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq
    cols = list(columns) if columns else None
    if os.path.isdir(path) and process_shard:
        # per-host file sharding needs the explicit part list (recursive:
        # hive-style key=value subdirectories keep their files)
        files = sorted(
            os.path.join(r, f) for r, _d, fs in os.walk(path)
            for f in fs if f.endswith((".parquet", ".pq")))
        if not files:
            raise ValueError(f"no parquet part files under {path}")
        sliced = _process_slice(files, True)
        if not sliced:
            # legitimately empty shard (more hosts than files): an empty
            # frame with the REAL schema, from a zero-row slice of part 0
            table = pq.read_table(files[0], columns=cols).slice(0, 0)
        else:
            table = pa.concat_tables(
                [pq.read_table(f, columns=cols) for f in sliced])
    else:
        # pyarrow natively reads files AND directories (incl. hive layout)
        table = pq.read_table(path, columns=cols)
    data: dict = {}
    for name in table.column_names:
        data[name] = _from_arrow(name, table.column(name))
    frame = Frame.from_dict(data)
    if not os.path.isdir(path) and process_shard:
        frame = frame.process_shard()  # single file: shard rows instead
    return (frame.repartition(num_partitions)
            if num_partitions > 1 and frame.count() else frame)


def _from_arrow(name: str, col) -> Any:
    """Arrow column -> Frame column storage, dispatched on the Arrow TYPE
    (never sniffed from values — null/empty rows must not change a
    column's meaning)."""
    import pyarrow as pa
    typ = col.type
    if pa.types.is_floating(typ) or pa.types.is_integer(typ) \
            or pa.types.is_boolean(typ):
        return col.to_numpy(zero_copy_only=False)
    if pa.types.is_list(typ) or pa.types.is_fixed_size_list(typ) \
            or pa.types.is_large_list(typ):
        vt = typ.value_type
        if pa.types.is_string(vt) or pa.types.is_large_string(vt):
            return [list(r) if r is not None else None
                    for r in col.to_pylist()]          # TOKENS
        rows = col.to_pylist()
        if not rows:
            width = typ.list_size if pa.types.is_fixed_size_list(typ) else 0
            return np.zeros((0, width), np.float32)     # empty VECTOR
        lens = {len(r) for r in rows if r is not None}
        if len(lens) == 1 and all(r is not None for r in rows):
            return np.asarray(rows, np.float32)         # uniform -> VECTOR
        # Frame has no ragged-numeric column type; refusing beats the
        # silent corruption of routing numbers through TOKENS
        raise ValueError(
            f"column {name!r} is a ragged or null-bearing numeric list "
            "(lengths {}); pad/clean it to uniform vectors first".format(
                sorted(lens)))
    return col.to_pylist()  # strings, binary, nulls -> object column


def write_parquet(frame: Frame, path: str) -> str:
    """Persist a Frame as one parquet file (VECTOR -> list<float>,
    TOKENS -> list<string>; IMAGE columns are not representable — drop or
    encode them first)."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    from mmlspark_tpu.core.schema import DType
    arrays, names = [], []
    for c in frame.schema:
        vals = frame.column(c.name)
        if c.dtype == DType.IMAGE:
            raise ValueError(
                f"column {c.name!r} is an IMAGE column; encode or drop it "
                "before write_parquet")
        if c.dtype == DType.VECTOR:
            arr = pa.array([None if v is None else [float(x) for x in v]
                            for v in vals])
        elif c.dtype == DType.TOKENS:
            arr = pa.array([None if v is None else [str(t) for t in v]
                            for v in vals])
        else:
            arr = pa.array(vals.tolist() if isinstance(vals, np.ndarray)
                           else list(vals))
        arrays.append(arr)
        names.append(c.name)
    pq.write_table(pa.table(arrays, names=names), path)
    return path


def _infer_csv_column(vals: List[str]):
    def try_parse(cast):
        out = []
        for v in vals:
            if v == "" or v is None:
                out.append(None)
            else:
                out.append(cast(v))
        return out
    try:
        ints = try_parse(int)
        return ints
    except ValueError:
        pass
    try:
        return try_parse(float)
    except ValueError:
        pass
    return [None if v == "" else v for v in vals]
