"""Sharding rules: map pytrees of arrays onto the mesh.

GSPMD style: we annotate shardings with ``NamedSharding`` and let XLA insert
the collectives (psum for gradient allreduce over ``data``+``fsdp``,
all-gather/reduce-scatter for fsdp params, all-to-all for expert dispatch) —
the in-compiler replacement for the reference's explicit MPI ring
(``CommandBuilders.scala:73-93``).

Rules are name-pattern based (à la t5x/flax partitioning): a list of
(regex, PartitionSpec) tried in order against the '/'-joined param path.
"""
from __future__ import annotations

import re
from functools import lru_cache
from typing import Any, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]

# Default rules for transformer/conv models on a (data, fsdp, ..., tensor) mesh:
# - large matmul weights: shard output features over `tensor`, input over `fsdp`
# - embeddings: shard vocab over `tensor`
# - biases/norm scales: replicated
DEFAULT_RULES: List[Tuple[str, P]] = [
    # MoE expert banks: leading E dim over `expert` (the all-to-all axis),
    # hidden dims over fsdp/tensor like their dense counterparts. The router
    # stays replicated — it is tiny and every token needs it.
    (r".*experts?_(up|wi|gate).*", P("expert", "fsdp", "tensor")),
    (r".*experts?_(down|wo|out).*", P("expert", "tensor", "fsdp")),
    (r".*router.*", P()),
    (r".*(attention|attn).*(query|key|value|qkv).*kernel", P("fsdp", "tensor")),
    (r".*(attention|attn).*out.*kernel", P("tensor", "fsdp")),
    (r".*mlp.*(up|gate|wi|fc1|intermediate).*kernel", P("fsdp", "tensor")),
    (r".*mlp.*(down|wo|fc2|output).*kernel", P("tensor", "fsdp")),
    # nn.Embed LEAVES only (path ends in 'embedding'): a trailing-anywhere
    # match also caught conv kernels under layers NAMED *_embedding (ViT's
    # patch_embedding/kernel) and sharded their SPATIAL dim over `tensor`
    # — which XLA's SPMD partitioner has been observed to silently
    # miscompile on the CPU backend, and would at best buy halo exchanges
    (r".*embedding$", P("tensor", None)),
    (r".*(head|logits|classifier).*kernel", P("fsdp", "tensor")),
    (r".*kernel", P(None, "fsdp")),   # generic dense/conv: shard last-in dim
    (r".*", P()),                     # everything else replicated
]

# Catch-all patterns in DEFAULT_RULES whose 2-D specs must NOT be stretched
# onto >2-D conv kernels — those get the spatial-safe default instead. Only
# consulted when the DEFAULT rules are in effect; caller-supplied rules are
# authoritative as written.
_GENERIC_PATTERNS = {r".*kernel", r".*"}

try:
    from jax import shard_map as _shard_map  # jax >= 0.5
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map_compat(f, mesh, in_specs, out_specs, check_vma=None):
    """``shard_map`` across jax versions: the function moved from
    ``jax.experimental.shard_map`` to top-level, and the replication-check
    kwarg renamed ``check_rep`` -> ``check_vma`` along the way. The one
    call shape sequence/pipeline parallel need, spelled once."""
    import inspect
    kwargs = {}
    if check_vma is not None:
        params = inspect.signature(_shard_map).parameters
        key = "check_vma" if "check_vma" in params else "check_rep"
        kwargs[key] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **kwargs)


def _path_str(path) -> str:
    parts = []
    for k in path:
        if hasattr(k, "key"):       # DictKey — falsy keys (0, '') included
            name = k.key
        elif hasattr(k, "name"):    # GetAttrKey
            name = k.name
        elif hasattr(k, "idx"):     # SequenceKey
            name = k.idx
        else:
            name = k
        parts.append(str(name))
    return "/".join(parts).lower()


def _fit_spec(spec: P, ndim: int, mesh: Mesh, shape) -> P:
    """Clamp a rule's PartitionSpec to the array's rank and divisibility.
    Axes the mesh doesn't carry count as size 1 (user-built meshes may
    name only the axes they use)."""
    entries = list(spec) + [None] * (ndim - len(spec))
    entries = entries[:ndim]
    fixed = []
    for dim, axis in zip(shape, entries):
        if axis is None:
            fixed.append(None)
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        size = np.prod([mesh.shape.get(a, 1) for a in axes])
        present = all(a in mesh.shape for a in axes)
        fixed.append(axis if present and size > 1 and dim % size == 0
                     else None)
    return P(*fixed)


def param_shardings(params: Any, mesh: Mesh,
                    rules: Optional[Rules] = None) -> Any:
    """NamedSharding pytree for model params using name-pattern rules."""
    using_defaults = rules is None
    rules = list(rules) if rules is not None else DEFAULT_RULES

    def conv_safe(ndim):
        # conv kernels (H, W, in, out) etc.: never shard spatial dims —
        # that buys halo collectives for nothing. Shard only the output
        # features (last dim) over fsdp when divisible.
        return P(*([None] * (ndim - 1) + ["fsdp"]))

    def assign(path, leaf):
        name = _path_str(path)
        ndim = getattr(leaf, "ndim", 0)
        shape = getattr(leaf, "shape", ())
        for pattern, spec in rules:
            if re.fullmatch(pattern, name):
                if (ndim > 2 and using_defaults
                        and pattern in _GENERIC_PATTERNS):
                    spec = conv_safe(ndim)
                return NamedSharding(mesh, _fit_spec(spec, ndim, mesh, shape))
        if ndim > 2:
            return NamedSharding(
                mesh, _fit_spec(conv_safe(ndim), ndim, mesh, shape))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(assign, params)


def pipeline_stacked_rules(base: Optional[Rules] = None,
                           prefix: str = "stages") -> List[Tuple[str, P]]:
    """Rules for a state tree containing a STACKED pipeline-stage subtree
    (leaves under ``prefix`` carry a leading stage dim, per
    ``pipeline_parallel.stack_stage_params``): every base rule is
    mirrored with ``prefix`` required in the path and ``pipe`` prepended
    to its spec — stage dim over the ``pipe`` axis, the remaining dims
    placed exactly as their non-pipelined counterparts — ahead of the
    unmodified base rules for the leaves outside the pipelined region
    (embed/head stay un-stacked). THE one home for the 3-D
    ``(data, tensor, pipe)`` placement policy (lint Rule 14): trainers
    composing ``pipeline_apply`` pass ``rules=pipeline_stacked_rules()``
    and the whole train state (params + optimizer mirrors) shards in one
    pass."""
    base = list(base) if base is not None else list(DEFAULT_RULES)
    anchor = r"(?=.*" + re.escape(prefix) + r"/)"
    staged = [(anchor + pat, P(*(("pipe",) + tuple(spec))))
              for pat, spec in base]
    return staged + base


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def tensor_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the model (``tensor``) axis; 1 for no mesh / axis absent."""
    return int(mesh.shape.get("tensor", 1)) if mesh is not None else 1


def embedding_table_sharding(mesh: Optional[Mesh]) -> NamedSharding:
    """Placement for an embedding table (rows, dim): rows over ``tensor``
    — the model-parallel split that lets a table bigger than one chip's
    HBM live on the mesh with each chip holding a contiguous row range
    (the same split DEFAULT_RULES' ``.*embedding$`` rule gives nn.Embed
    leaves, spelled once for the embed/ subsystem). Replicated when the
    mesh has no non-trivial ``tensor`` axis."""
    if tensor_axis_size(mesh) > 1:
        return NamedSharding(mesh, P("tensor", None))
    return NamedSharding(mesh, P())


def embedding_lookup_specs(mesh: Mesh) -> Tuple[P, P, P]:
    """``(table, ids, out)`` PartitionSpecs for the embed/ fused-lookup
    ``shard_map``: table rows over ``tensor``, the id batch over the data
    axes (replicated over ``tensor`` — every model shard sees every id so
    it can answer for the rows it owns), bags back over the data axes.
    Weights share the ids spec. THE one place these specs are written
    (lint Rule 14); ``embed/tables.py`` imports them."""
    axes = active_batch_axes(mesh)
    return P("tensor", None), P(axes, None), P(axes, None)


def kv_arena_sharding(mesh: Mesh, heads: int) -> NamedSharding:
    """Placement for a paged KV arena (layers, blocks, block_tokens, heads,
    head_dim): the head axis over ``tensor`` when the model axis is
    non-trivial and divides the head count — the same split the attention
    projections use, so each model shard attends over exactly the heads it
    computed, with no cross-shard gather of K/V. Otherwise replicated."""
    t = tensor_axis_size(mesh)
    if t > 1 and heads % t == 0:
        return NamedSharding(mesh, P(None, None, None, "tensor", None))
    return NamedSharding(mesh, P())


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """Quantization scales (layers, blocks, block_tokens) carry no head
    axis — replicate them (they are ~head_dim x smaller than the arena)."""
    return NamedSharding(mesh, P())


def epoch_cache_sharding(mesh: Mesh, ndim: int,
                         seq_axis: Optional[str] = None) -> NamedSharding:
    """Placement for a device-resident epoch cache array (E, B, ...): the
    leading epoch dim replicated, batch over the data axes, and — for >2-D
    arrays when requested — the third (sequence) dim over ``seq``."""
    axes = active_batch_axes(mesh)
    if ndim > 2 and seq_axis and mesh.shape.get(seq_axis, 1) > 1:
        return NamedSharding(mesh, P(None, axes, seq_axis))
    return NamedSharding(mesh, P(None, axes))


BATCH_AXES = ("data", "fsdp")


def active_batch_axes(mesh: Mesh,
                      batch_axes: Sequence[str] = BATCH_AXES):
    """The non-trivial data-parallel axes of this mesh (None if all size 1).

    THE single definition of which axes shard the batch dimension — the
    sequence- and pipeline-parallel modules build their shard_map specs from
    this too, so the policy can't drift between modules.
    """
    return tuple(a for a in batch_axes if mesh.shape.get(a, 1) > 1) or None


def batch_sharding(mesh: Mesh, batch_axes: Sequence[str] = BATCH_AXES,
                   seq_axis: Optional[str] = None) -> NamedSharding:
    """Batch dim sharded over the data-parallel axes; optionally the second
    (sequence) dim over `seq` for context parallelism."""
    axes = active_batch_axes(mesh, batch_axes)
    if seq_axis and mesh.shape.get(seq_axis, 1) > 1:
        return NamedSharding(mesh, P(axes, seq_axis))
    return NamedSharding(mesh, P(axes))


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when the mesh contains devices this process cannot address —
    the multi-host case where a plain ``device_put`` would raise."""
    return _spans(mesh)


def is_cpu_mesh(mesh: Mesh) -> bool:
    """True when the mesh runs on the CPU collective runtime — which
    needs serialized multi-device program streams (its collective
    rendezvous can deadlock/starve under concurrent or deeply queued
    programs). Keyed on the MESH's devices, not ``default_backend()``:
    a CPU-device mesh on an accelerator host is still the CPU runtime."""
    return mesh.devices.flat[0].platform == "cpu"


@lru_cache(maxsize=None)
def _spans(mesh: Mesh) -> bool:
    pid = jax.process_index()
    return any(d.process_index != pid for d in mesh.devices.flat)


@lru_cache(maxsize=None)
def batch_share(mesh: Mesh, axes: Optional[Tuple[str, ...]] = None
                ) -> Tuple[int, int]:
    """(local, total) batch-dim shard counts for this process.

    ``total`` is how many blocks the batch dimension splits into over the
    data axes; ``local`` is how many of those blocks have at least one
    device owned by this process. A process's share of a global batch of
    ``b`` rows is ``b * local / total`` — THE division of labor for
    per-host batch assembly (each host feeds only the rows its devices
    hold, the TPU-native replacement for the reference's shared-filesystem
    hand-off where every MPI rank re-read the whole dataset).
    """
    axes = active_batch_axes(mesh) if axes is None else axes
    if not axes:
        return 1, 1
    names = list(mesh.axis_names)
    dev = mesh.devices
    ax_idx = [names.index(a) for a in axes]
    order = ax_idx + [i for i in range(dev.ndim) if i not in ax_idx]
    total = int(np.prod([dev.shape[i] for i in ax_idx]))
    blocks = np.transpose(dev, order).reshape(total, -1)
    pid = jax.process_index()
    local = sum(1 for i in range(total)
                if any(d.process_index == pid for d in blocks[i]))
    return local, total


def local_batch_rows(mesh: Mesh, global_rows: int) -> int:
    """Rows of a ``global_rows`` batch this process must supply.

    THE one place the division of labor is computed — shard_batch and
    DeviceEpochCache both defer here, so the share formula cannot drift."""
    local, total = batch_share(mesh)
    if global_rows % total:
        raise ValueError(
            f"global batch of {global_rows} rows does not split into "
            f"{total} equal batch shards")
    return global_rows // total * local


def shard_batch(mesh: Mesh, batch: Any,
                seq_axis: Optional[str] = None) -> Any:
    """Place a host batch onto the mesh, sharded over data axes.

    This is the host->HBM hand-off replacing the reference's shared-filesystem
    data channel (``DataConversion.scala:106-173``): one device_put of a
    contiguous host array per input, no text files, no per-element copies.

    Under a multi-process launch (``mesh_spans_processes``), ``batch`` holds
    this process's LOCAL rows — ``local_batch_rows(mesh, b)`` of a global
    batch of ``b`` — and the global array assembles from every process's
    contribution without any cross-host copy of the data itself (each
    host's rows land on its own devices; only metadata rendezvous).
    Global row order is process order: process 0's rows first.
    """
    spans = mesh_spans_processes(mesh)

    def put(x):
        x = np.asarray(x)
        sharding = batch_sharding(mesh, seq_axis=seq_axis if x.ndim > 1 else None)
        if spans:
            local, total = batch_share(mesh)
            if x.shape[0] % local:
                raise ValueError(
                    f"local batch of {x.shape[0]} rows does not split into "
                    f"this process's {local} batch shards (of {total} "
                    "global)")
            gshape = (x.shape[0] // local * total,) + x.shape[1:]
            return jax.make_array_from_process_local_data(sharding, x, gshape)
        return jax.device_put(x, sharding)
    return jax.tree_util.tree_map(put, batch)
