"""Pipeline parallelism over the ``pipe`` mesh axis.

GPipe-style microbatch schedule, expressed the TPU way: every pipe rank
holds ONE stage's params (a stacked pytree sharded over ``pipe``), and a
single ``lax.scan`` of M + S - 1 ticks moves activations rank->rank with
``ppermute`` — a neighbor ICI hop per tick, no host involvement. The whole
schedule is one XLA program; reverse-mode AD differentiates through it
(ppermute's transpose is the reverse permute), so the backward pass is the
mirrored pipeline automatically.

Constraints (standard for pipelined transformer stacks):
- every stage maps activations to the SAME shape (embed/head layers belong
  outside the pipelined region);
- global batch must divide into ``n_microbatches`` equal microbatches.

Bubble fraction is (S-1)/(M+S-1): choose n_microbatches >= 4*|pipe| to keep
it small.

Composes with the other axes: batch stays sharded over data/fsdp inside the
shard_map; tensor/seq parallel can live inside ``stage_fn``.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from mmlspark_tpu.parallel.sharding import shard_map_compat as shard_map
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.parallel.sharding import active_batch_axes


def stack_stage_params(params_list: Sequence[Any]) -> Any:
    """Per-stage param pytrees -> one pytree with a leading stage dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *params_list)


def init_stage_params(stage_init: Callable[[jax.Array, int], Any],
                      n_stages: int, rng: jax.Array) -> Any:
    """Initialize S stages with distinct keys; returns the stacked pytree.

    ``stage_init(key, stage_index) -> params`` for one stage.
    """
    keys = jax.random.split(rng, n_stages)
    return stack_stage_params(
        [stage_init(keys[i], i) for i in range(n_stages)])


def pipeline_spec(mesh: Mesh, pipe_axis: str = "pipe") -> P:
    """PartitionSpec for stacked stage params: stage dim over ``pipe``."""
    return P(pipe_axis)  # lint: allow-spec (shard_map axis local to this module)


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any, x: jnp.ndarray, mesh: Mesh,
                   n_microbatches: int, pipe_axis: str = "pipe") -> jnp.ndarray:
    """Run x through S pipelined stages; returns the last stage's output.

    stacked_params: pytree whose leaves have leading dim n_stages (sharded
    over ``pipe``); x: (B, ...) activations entering stage 0. n_stages may
    be any multiple of |pipe|: each rank chains its contiguous block of
    stages per tick (virtual-pipeline super-stages), so an 8-layer stack on
    a 4-rank pipe computes layers [0,1] -> [2,3] -> [4,5] -> [6,7].
    """
    S = mesh.shape.get(pipe_axis, 1)
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if n_stages % S:
        raise ValueError(
            f"stacked stage count {n_stages} must be a multiple of "
            f"|{pipe_axis}|={S}")
    if S == 1:
        def body(x, i):
            p = jax.tree_util.tree_map(lambda a: a[i], stacked_params)
            return stage_fn(p, x), None
        out, _ = jax.lax.scan(body, x, jnp.arange(n_stages))
        return out

    B = x.shape[0]
    M = n_microbatches
    batch = active_batch_axes(mesh)
    n_data_shards = int(np.prod([mesh.shape[a] for a in (batch or ())]))
    local_B = B // max(n_data_shards, 1)
    if B % max(n_data_shards, 1) or local_B % M:
        raise ValueError(
            f"per-data-shard batch {B}/{n_data_shards} must divide into "
            f"n_microbatches={M}")
    x_spec = P(batch)  # lint: allow-spec (shard_map in/out spec)

    k_local = n_stages // S  # stages chained per rank (virtual pipeline)

    def local(params, x):
        idx = jax.lax.axis_index(pipe_axis)
        mb = x.shape[0] // M
        xs = x.reshape((M, mb) + x.shape[1:])
        perm = [(i, i + 1) for i in range(S - 1)]
        zero = jnp.zeros_like(xs[0])

        def super_stage(params, x):
            def body(x, i):
                p = jax.tree_util.tree_map(lambda a: a[i], params)
                return stage_fn(p, x), None
            out, _ = jax.lax.scan(body, x, jnp.arange(k_local))
            return out

        def tick(carry, t):
            recv, acc = carry
            mb_idx = t - idx
            feed = xs[jnp.clip(mb_idx, 0, M - 1)]
            inp = jnp.where(idx == 0, feed, recv)
            out = super_stage(params, inp)
            active = (mb_idx >= 0) & (mb_idx < M)
            out = jnp.where(active, out, 0.0)
            # last rank banks each microbatch as it completes
            bank = jnp.where(active & (idx == S - 1), out, 0.0)
            acc = acc.at[jnp.clip(mb_idx, 0, M - 1)].add(bank)
            recv = jax.lax.ppermute(out, pipe_axis, perm)
            return (recv, acc), None

        acc0 = jnp.zeros_like(xs)
        (_, acc), _ = jax.lax.scan(
            tick, (zero, acc0), jnp.arange(M + S - 1))
        # outputs live on the last rank only: psum broadcasts them everywhere
        acc = jax.lax.psum(
            jnp.where(idx == S - 1, acc, jnp.zeros_like(acc)), pipe_axis)
        return acc.reshape(x.shape)

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(pipeline_spec(mesh, pipe_axis), x_spec),
        out_specs=x_spec, check_vma=False)
    return fn(stacked_params, x)
