"""Device mesh construction + multi-host initialization.

The TPU-native replacement for the reference's distributed launch machinery:

- device discovery: ``jax.devices()`` replaces shelling out to ``nvidia-smi``
  (``core/env/src/main/scala/EnvironmentUtils.scala:20-50``);
- multi-host: ``jax.distributed.initialize`` replaces the MPI hostfile
  launcher (``cntk-train/src/main/scala/CommandBuilders.scala:95-117``);
- the mesh axes are the vocabulary the whole parallel layer speaks:
  ``data`` (batch), ``fsdp`` (sharded params+batch), ``tensor`` (intra-layer
  model parallel), ``pipe`` (pipeline stages), ``seq`` (sequence/context
  parallel for long inputs), ``expert`` (MoE).

Axis layout matters physically: the LAST mesh dimensions map to the
innermost (fastest, torus-adjacent) ICI rings on real TPU slices, so
``tensor``/``seq`` — the axes with per-step collectives — are placed last.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "fsdp", "pipe", "expert", "seq", "tensor")


@dataclass(frozen=True)
class MeshSpec:
    """Sizes per logical axis; -1 on `data` means "absorb remaining devices"."""
    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {"data": self.data, "fsdp": self.fsdp, "pipe": self.pipe,
                 "expert": self.expert, "seq": self.seq, "tensor": self.tensor}
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if n_devices % fixed:
            raise ValueError(
                f"{n_devices} devices not divisible by fixed axes product {fixed}")
        free = [k for k, v in sizes.items() if v == -1]
        if len(free) > 1:
            raise ValueError(f"only one axis may be -1, got {free}")
        if free:
            sizes[free[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(
                f"axis sizes {sizes} do not multiply to {n_devices} devices")
        return sizes


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None) -> Mesh:
    """Build a Mesh over all (or given) devices with the standard axis order."""
    devices = list(devices) if devices is not None else jax.devices()
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def data_parallel_mesh(devices: Optional[Sequence] = None) -> Mesh:
    return make_mesh(MeshSpec(data=-1), devices)


def parse_mesh_axes(text: str) -> Dict[str, int]:
    """'data=-1,tensor=2' -> {'data': -1, 'tensor': 2}, validated against
    AXES. The one parser behind both the launcher's --mesh flag and the
    ``runtime.mesh`` config key."""
    axes: Dict[str, int] = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        axis, eq, size = part.partition("=")
        if not eq or not size:
            raise ValueError(f"bad mesh entry {part!r}: want axis=size")
        if axis not in AXES:
            raise ValueError(f"unknown mesh axis {axis!r}; have {AXES}")
        n = int(size)
        if n == 0 or n < -1:
            raise ValueError(
                f"bad size {n} for mesh axis {axis!r}: want a positive "
                "size or -1 (absorb remaining devices)")
        axes[axis] = n
    return axes


def parse_mesh_shape(text: str) -> MeshSpec:
    """'4x2' -> MeshSpec(data=4, tensor=2): the (data, model[, pipe])
    shorthand behind the ``parallel.mesh_shape`` config key. The first
    factor is the data axis (-1 absorbs remaining devices), the second the
    model (``tensor``) axis — placed last so per-layer collectives ride the
    innermost ICI ring — and an optional third factor is the ``pipe``
    (pipeline-stage) axis: '2x2x2' lays a 3-D (data=2, tensor=2, pipe=2)
    topology. A single factor ('8') means pure data parallel."""
    parts = [p.strip() for p in text.lower().split("x") if p.strip()]
    if not parts or len(parts) > 3:
        raise ValueError(
            f"bad mesh shape {text!r}: want 'DATAxMODEL' (e.g. '4x2'), "
            "'DATAxMODELxPIPE' (e.g. '2x2x2'), or a single data-parallel "
            "size")
    sizes = [int(p) for p in parts]
    for n in sizes:
        if n == 0 or n < -1:
            raise ValueError(
                f"bad size {n} in mesh shape {text!r}: want a positive "
                "size or -1 (absorb remaining devices)")
    if len(sizes) == 1:
        return MeshSpec(data=sizes[0])
    if any(n == -1 for n in sizes[1:]):
        raise ValueError(
            f"bad mesh shape {text!r}: only the data factor may be -1")
    if len(sizes) == 2:
        return MeshSpec(data=sizes[0], tensor=sizes[1])
    return MeshSpec(data=sizes[0], tensor=sizes[1], pipe=sizes[2])


def mesh_from_config(devices: Optional[Sequence] = None) -> Mesh:
    """Mesh from config: ``parallel.mesh_shape`` (the 2-D 'DxT' shorthand,
    e.g. '4x2') first, else the ``runtime.mesh`` axis-map key (set by the
    launcher's ``--mesh data=-1,tensor=2`` flag or MMLSPARK_TPU_RUNTIME_MESH).
    Falls back to all-devices data parallel when both are unset — so library
    code can default to this and the same script scales by flag alone."""
    from mmlspark_tpu.utils import config
    shape = config.get("parallel.mesh_shape", "")
    if shape:
        return make_mesh(parse_mesh_shape(shape), devices)
    text = config.get("runtime.mesh")
    if not text:
        return data_parallel_mesh(devices)
    return make_mesh(MeshSpec(**parse_mesh_axes(text)), devices)


def resolve_mesh(mesh_spec) -> Mesh:
    """MeshSpec | axis-size dict | "data=2,tensor=4" string | Mesh | None
    -> Mesh. None consults the launcher's ``runtime.mesh`` config (falling
    back to all-devices data parallel), so ``mmlspark-tpu run train.py
    --mesh data=2,tensor=4`` reshapes TRAINING without touching the
    script; the string form is the same syntax as that flag. (JaxModel
    scoring treats an unset meshSpec as the single-device fast path
    instead — scoring rarely needs a mesh and must not silently change
    shape under a launcher flag meant for training.)"""
    if mesh_spec is None:
        return mesh_from_config()
    if isinstance(mesh_spec, Mesh):
        return mesh_spec
    if isinstance(mesh_spec, str):
        mesh_spec = parse_mesh_axes(mesh_spec)
    if isinstance(mesh_spec, dict):
        unknown = sorted(set(mesh_spec) - set(AXES))
        if unknown:
            raise ValueError(
                f"unknown mesh axes {unknown}; valid axes are {AXES}")
        mesh_spec = MeshSpec(**mesh_spec)
    return make_mesh(mesh_spec)


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join the jax.distributed process group (idempotent).

    One program domain replaces the reference's three-channel split
    (Spark RPC + MPI ring + shared filesystem, SURVEY.md §2.6): after this
    call every host sees the global device set and collectives ride ICI
    within a slice / DCN across slices.
    """
    # Do NOT probe jax.process_count() here: it initializes the backend,
    # after which distributed init is impossible. "Already initialized" is
    # detected from initialize()'s own error instead of private state.
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    elif num_processes is not None or process_id is not None:
        # Worker flags without a coordinator would silently train alone
        # while the rest of the cluster hangs at the barrier — refuse.
        raise ValueError(
            "num_processes/process_id were given without a "
            "coordinator_address; pass all three (or none, for "
            "single-process / auto-detected cluster runs)")
    else:
        # Convenience call with nothing to join: if a backend is already
        # live in this process (interactive session, test runner), starting
        # a coordination service now can abort later XLA work — skip.
        # Reading the backend cache does NOT initialize it.
        from jax._src import xla_bridge
        if getattr(xla_bridge, "_backends", None):
            return
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            return
        if coordinator_address is None and "backend" in msg:
            # single-process convenience call after the backend is live
            # (e.g. `mmlspark-tpu run` inside an interactive session):
            # nothing to join, nothing to do
            return
        raise  # a real multi-host init failure must not be silent
    except ValueError:
        if coordinator_address is not None:
            raise  # explicit cluster config that failed is an error
        # else: no cluster auto-detected — single-process dev/test env


def device_count_summary() -> Dict[str, int]:
    """The `nvidia-smi -L` replacement: structured device inventory."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
