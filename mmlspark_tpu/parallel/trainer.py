"""DistributedTrainer: pjit-sharded training steps over the device mesh.

The in-process replacement for the reference's distributed training path
(``CNTKLearner.fit`` writing text files + launching ``mpiexec -n G cntk ...
parallelTrain=true``, ``cntk-train/src/main/scala/CNTKLearner.scala:52-162``):

- no subprocess: the train step is one jitted XLA program;
- no MPI ring: gradients allreduce via the collectives XLA inserts from the
  GSPMD shardings (psum over ``data``/``fsdp`` riding ICI);
- no filesystem hand-off: host batches stream via ``shard_batch``;
- multi-host via ``jax.distributed`` (mesh.py) instead of hostfiles.

Supports dp / fsdp / tensor-parallel out of the box through the sharding
rules; pipeline and sequence parallel live in their own modules and compose
via the same mesh.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

# DevicePrefetcher moved to data/prefetch.py (the streaming input pipeline's
# terminal stage); re-exported here because trainer.DevicePrefetcher is the
# documented import path for existing callers (train/deep.py, tests).
from mmlspark_tpu.data.pipeline import Dataset
from mmlspark_tpu.data.prefetch import DevicePrefetcher  # noqa: F401
from mmlspark_tpu.parallel.mesh import mesh_from_config
from mmlspark_tpu.observability import events as obsevents
from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.observability import syncs as obssyncs
from mmlspark_tpu.reliability import watchdog as _watchdog
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.parallel.sharding import (
    batch_sharding, epoch_cache_sharding, is_cpu_mesh, local_batch_rows,
    mesh_spans_processes, param_shardings, replicated, Rules, shard_batch,
)
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import MetricLogger, get_logger

LossFn = Callable[[Any, Dict[str, jax.Array], jax.Array], jax.Array]


_SPLIT_JIT = None


def _shared_split_jit():
    """One process-wide jitted epoch splitter shared by every cache
    instance (a per-instance jit would re-trace, and on remote-compile
    backends re-compile, for every fresh cache). The step count is a
    STATIC argument: all indices are compile-time constants, so
    materializing an epoch is ONE dispatch with zero host->device scalar
    transfers — the previous per-batch traced-index slicer shipped a
    scalar per batch, and on a tunneled chip each of those scalar puts
    stalls the pipeline ~17 ms (672 ms to materialize a 40-step epoch;
    this program does it in one round trip)."""
    global _SPLIT_JIT
    if _SPLIT_JIT is None:
        _SPLIT_JIT = jax.jit(
            lambda d, steps: [
                jax.tree_util.tree_map(lambda a: a[i], d)
                for i in range(steps)],
            static_argnums=1)
    return _SPLIT_JIT


class DeviceEpochCache:
    """Device-resident epoch: one host->HBM transfer, batches sliced on device.

    Streaming a host batch per step is the CNTKModel anti-pattern's last
    residue — on links where host->HBM transfers contend with execution
    (PCIe under load, tunneled chips), every per-step ``device_put`` stalls
    the pipeline. When the (featurized) epoch fits in an HBM budget, the
    TPU-first move is residency: transfer once, then every batch is an XLA
    slice of an already-on-device array — zero steady-state transfer.

    Layout: each column is reshaped host-side to ``(steps, batch, ...)`` and
    placed with the BATCH dim (axis 1) sharded over the mesh's data axes, so
    slicing out batch ``i`` along the replicated axis 0 moves no data across
    devices and yields exactly the sharding ``put_batch`` would have
    committed. Optional per-epoch shuffling permutes rows on device with a
    ``fold_in(seed, epoch)`` key — deterministic, so elastic resume replays
    the same order (the contract DeepClassifier's streaming path keeps).

    Rows beyond ``steps * batch_size`` are dropped; callers that need the
    tail pad-and-mask FIRST (``_pad_xyw``) and let the pad rows ride along
    with zero weight.

    Multi-process: ``batch_size`` is the GLOBAL batch and ``data`` holds
    this process's LOCAL rows — its ``batch_share`` of every batch, in
    process order (process 0's rows sort first within each batch). The
    epoch assembles into one global jax.Array whose shards live on each
    host's own devices; the device-side shuffle then permutes GLOBALLY
    (same fold_in key on every process under SPMD), so batch composition
    is identical to a single-process cache over the concatenated rows.
    """

    def __init__(self, data: Dict[str, np.ndarray], batch_size: int,
                 mesh: Optional[Mesh] = None, seq_axis: Optional[str] = None,
                 shuffle: bool = False, seed: int = 0):
        self.mesh = mesh or mesh_from_config()
        self.batch_size = int(batch_size)
        self._spans = mesh_spans_processes(self.mesh)
        self.local_batch = (local_batch_rows(self.mesh, self.batch_size)
                            if self._spans else self.batch_size)
        first = next(iter(data.values()))
        n = first.shape[0]
        self.steps_per_epoch = n // self.local_batch
        if self.steps_per_epoch < 1:
            raise ValueError(
                f"epoch of {n} local rows is smaller than the local batch "
                f"{self.local_batch}")
        self.shuffle = shuffle
        self.seed = seed
        self._epoch: Optional[int] = None

        keep = self.steps_per_epoch * self.local_batch
        if keep < n:
            import warnings
            warnings.warn(
                f"DeviceEpochCache drops {n - keep} of {n} rows beyond "
                f"steps*batch_size ({self.steps_per_epoch}*{self.local_batch});"
                " pad-and-mask the tail first (learners._pad_xyw) to train on"
                " every row", stacklevel=2)
        with self.mesh:
            def put(name, x):
                x = np.ascontiguousarray(
                    np.asarray(x)[:keep].reshape(
                        (self.steps_per_epoch, self.local_batch)
                        + np.asarray(x).shape[1:]))
                sharding = epoch_cache_sharding(self.mesh, x.ndim,
                                                seq_axis=seq_axis)
                if self._spans:
                    gshape = ((self.steps_per_epoch, self.batch_size)
                              + x.shape[2:])
                    return jax.make_array_from_process_local_data(
                        sharding, x, gshape)
                return jax.device_put(x, sharding)

            base = {k: put(k, v) for k, v in data.items()}
            self._nbytes = sum(int(a.nbytes) for a in base.values())
            self._split = _shared_split_jit()
            if shuffle:
                self._base = base
                self._batches = None  # built per epoch in batches()
                def permute(d, key):
                    m = self.steps_per_epoch * self.batch_size
                    perm = jax.random.permutation(key, m)
                    def one(a):
                        flat = a.reshape((m,) + a.shape[2:])
                        return jnp.take(flat, perm, axis=0).reshape(a.shape)
                    return jax.tree_util.tree_map(one, d)
                self._permute = jax.jit(
                    permute,
                    out_shardings=jax.tree_util.tree_map(
                        lambda a: a.sharding, base))
            else:
                # materialize once; the epoch tensor itself is then free
                self._base = None
                self._batches = self._materialize(base)

    @property
    def nbytes(self) -> int:
        return self._nbytes

    @staticmethod
    def fits(data: Dict[str, np.ndarray],
             budget_mb: Optional[float] = None,
             shuffle: bool = False) -> bool:
        """Would this host epoch fit the ``runtime.device_cache_mb`` budget?
        ``data`` may hold real arrays OR shape/dtype-only stand-ins (e.g.
        ``np.broadcast_to`` views), so callers can budget-check WITHOUT
        materializing the epoch. ``shuffle=True`` charges 3x: base + the
        transient permuted tensor + the materialized batch slices are all
        simultaneously resident at the peak of each epoch's shuffle.
        Unshuffled charges 2x for the build-time peak (epoch tensor + its
        slices; the tensor frees after)."""
        if budget_mb is None:
            budget_mb = float(mmlconfig.get("runtime.device_cache_mb"))
        total = sum(np.asarray(v).nbytes for v in data.values())
        return total * (3 if shuffle else 2) <= budget_mb * 1e6

    def _materialize(self, tensor_dict):
        """Slice the (steps, batch, ...) epoch into per-batch arrays.

        The split program is queued AHEAD of any consumer step, so the
        runtime's program order already guarantees batches exist before a
        step reads them — the host does not need to wait, and on remote/
        tunneled chips a synchronous wait here serializes (transfer, then
        step dispatch) where async overlaps them (~0.5 s per epoch staging
        on a congested link). The CPU runtime is the exception and DOES
        block: its collective rendezvous can deadlock when a second
        multi-device program stream interleaves with step collectives."""
        with self.mesh:
            batches = self._split(tensor_dict, self.steps_per_epoch)
            if is_cpu_mesh(self.mesh):
                obssyncs.block_until_ready(batches, "trainer.materialize")
        return batches

    def batches(self, epoch: int = 0):
        """Device batch dicts for one epoch (shuffled iff ``shuffle``)."""
        if self.shuffle and self._epoch != epoch:
            with self.mesh:
                permuted = self._permute(
                    self._base, jax.random.fold_in(
                        jax.random.PRNGKey(self.seed), epoch))
            # permuted frees after slicing; steady state = base + batches
            self._batches = self._materialize(permuted)
            self._epoch = epoch
        yield from self._batches


class DistributedTrainer:
    """Builds sharded init/train/eval steps for a pure loss function.

    loss_fn(params, batch, rng) -> scalar loss (fp32). The whole step —
    forward, backward, allreduce, optimizer — compiles to one XLA program.
    """

    def __init__(self, loss_fn: LossFn, optimizer: optax.GradientTransformation,
                 mesh: Optional[Mesh] = None, rules: Optional[Rules] = None,
                 accum_steps: int = 1, seq_axis: Optional[str] = None,
                 remat: bool = False):
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        # default honors the launcher's --mesh/runtime.mesh (all-devices
        # data-parallel when unset), like DeepClassifier's mesh resolution
        self.mesh = mesh or mesh_from_config()
        self.rules = rules
        self.accum_steps = accum_steps
        self.seq_axis = seq_axis
        self.remat = remat
        self._state_shardings = None
        # two jitted step variants, keyed by whether the batch buffers are
        # donated (fit's streaming path donates; direct callers feeding
        # reused device batches — DeviceEpochCache epochs — must not)
        self._train_steps: Dict[bool, Any] = {}
        self._eval_step = None
        # Device-resident metrics ring (ROADMAP item 4, "kill the overhead
        # floor"): per-step scalars (loss, step counter) accumulate in a
        # ring CARRIED THROUGH the jitted step instead of a host-side list
        # of device scalars, so steady-state stepping performs ZERO host
        # syncs. The ring is fetched ("flushed") once every
        # ``train.metrics_flush_steps`` steps; on the multi-device CPU
        # runtime that flush doubles as the dispatch-depth throttle (its
        # collective rendezvous can starve under hundreds of queued async
        # steps — real TPU runtimes bound their own launch queue).
        self._ring: Optional[Dict[str, jax.Array]] = None
        self._flush_steps: Optional[int] = None  # resolved at first step
        self._steps_since_flush = 0
        self._throttled = is_cpu_mesh(self.mesh)
        self._flops_per_step: Optional[float] = None  # lazy cost analysis

    # -- state -------------------------------------------------------------
    def _full_init_fn(self, init_params_fn: Callable[[], Any]):
        def full_init():
            params = init_params_fn()
            return {"params": params,
                    "opt_state": self.optimizer.init(params),
                    "step": jnp.zeros((), jnp.int32)}
        return full_init

    def _abstract_state(self, full_init):
        abstract = jax.eval_shape(full_init)
        # Optimizer state mirrors the param tree (adam mu/nu paths contain the
        # same leaf names), so one rule pass shards params AND opt state.
        self._state_shardings = param_shardings(abstract, self.mesh, self.rules)
        return abstract, self._state_shardings

    def abstract_state(self, init_params_fn: Callable[[], Any]):
        """(abstract shapes, shardings) of the train state WITHOUT
        materializing it — the checkpoint-restore target (checkpoint.py).
        Also establishes this trainer's sharding spec."""
        return self._abstract_state(self._full_init_fn(init_params_fn))

    def init(self, init_params_fn: Callable[[], Any]) -> Dict[str, Any]:
        """Initialize sharded state; params materialize directly into their
        shards (no host-side full copy on any single device)."""
        full_init = self._full_init_fn(init_params_fn)
        self._abstract_state(full_init)
        with self.mesh:
            return jax.jit(full_init, out_shardings=self._state_shardings)()

    def state_sharding_spec(self) -> Any:
        return self._state_shardings

    # -- steps -------------------------------------------------------------
    def flush_steps(self) -> int:
        """Steps between metric-ring flushes (``train.metrics_flush_steps``,
        resolved once at first use — the ring length is a compile-time
        constant of the step program)."""
        if self._flush_steps is None:
            self._flush_steps = max(
                1, int(mmlconfig.get("train.metrics_flush_steps")))
        return self._flush_steps

    def _init_ring(self) -> Dict[str, jax.Array]:
        """Fresh device-resident metrics ring: a ``flush_steps``-long loss
        ring plus the step counter of the latest step written. Replicated
        on purpose — every process flushes identical values under SPMD."""
        flush = self.flush_steps()
        repl = replicated(self.mesh)
        with self.mesh:
            return {
                "loss": jax.device_put(
                    np.zeros((flush,), np.float32), repl),
                "step": jax.device_put(np.zeros((), np.int32), repl),
            }

    def _build_train_step(self, donate_batch: bool):
        loss_fn = self.loss_fn
        if self.remat:
            loss_fn = jax.checkpoint(loss_fn)
        accum = self.accum_steps
        flush = self.flush_steps()

        def single_grad(params, batch, rng):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
            return loss, grads

        def step(state, ring, batch, rng):
            params = state["params"]
            rng = jax.random.fold_in(rng, state["step"])
            if accum > 1:
                # microbatch gradient accumulation via scan: trades HBM for
                # one weight update per `accum` forward/backward passes
                def micro(carry, mb_and_idx):
                    mb, idx = mb_and_idx
                    loss_acc, grad_acc = carry
                    # distinct rng per microbatch (dropout must differ)
                    loss, grads = single_grad(params, mb,
                                              jax.random.fold_in(rng, idx))
                    return (loss_acc + loss,
                            jax.tree_util.tree_map(jnp.add, grad_acc, grads)), None
                microbatches = jax.tree_util.tree_map(
                    lambda x: x.reshape((accum, x.shape[0] // accum) + x.shape[1:]),
                    batch)
                zero = jax.tree_util.tree_map(jnp.zeros_like, params)
                (loss, grads), _ = jax.lax.scan(
                    micro, (0.0, zero), (microbatches, jnp.arange(accum)))
                loss = loss / accum
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
            else:
                loss, grads = single_grad(params, batch, rng)
            updates, opt_state = self.optimizer.update(
                grads, state["opt_state"], params)
            new_params = optax.apply_updates(params, updates)
            new_state = {"params": new_params, "opt_state": opt_state,
                         "step": state["step"] + 1}
            # metrics ring: the loss lands in slot (step mod flush) ON
            # device — no per-step host traffic; the host reads the whole
            # ring once per flush interval
            new_ring = {"loss": ring["loss"].at[
                jnp.mod(state["step"], flush)].set(loss),
                "step": new_state["step"]}
            return new_state, new_ring, {"loss": loss}

        # Batch shardings are NOT pinned here: put_batch commits per-leaf
        # shardings (rank-aware — labels are rank-1, activations rank-N) and
        # jit infers from the committed arrays. Pinning a rank-2 spec on the
        # whole batch dict would crash on rank-1 leaves. Donation extends
        # the same rank-awareness: state and ring always donate (their
        # buffers are dead the instant the step returns); the batch donates
        # only on the streaming path (argnum 2, per-leaf committed
        # shardings), where each put_batch transfer is single-use — donating
        # it stops the step from double-buffering its inputs. Reused device
        # batches (DeviceEpochCache epochs) take the non-donating variant.
        ring_shardings = {"loss": replicated(self.mesh),
                          "step": replicated(self.mesh)}
        return jax.jit(
            step,
            out_shardings=(self._state_shardings, ring_shardings, None),
            donate_argnums=(0, 1, 2) if donate_batch else (0, 1))

    def _get_train_step(self, donate_batch: bool):
        fn = self._train_steps.get(donate_batch)
        if fn is None:
            if self._state_shardings is None:
                raise RuntimeError("call init() before train_step()")
            fn = self._build_train_step(donate_batch)
            self._train_steps[donate_batch] = fn
        return fn

    def train_step(self, state, batch, rng, *,
                   donate_batch: bool = False
                   ) -> Tuple[Any, Dict[str, jax.Array]]:
        """One async sharded step. ``donate_batch=True`` additionally
        donates the batch buffers to the step program (no input
        double-buffering) — callers must treat those device arrays as
        CONSUMED; ``fit``'s streaming path opts in, DeviceEpochCache
        consumers that replay batches across epochs must not."""
        # reliability hook: a FaultPlan can kill the Nth step to reproduce a
        # preemption bit-for-bit (a no-op global read when no plan is active)
        fault_site("trainer.train_step")
        fn = self._get_train_step(donate_batch)
        if self._ring is None:
            self._ring = self._init_ring()
        with self.mesh:
            if donate_batch:
                # batch donation is best-effort: leaves whose buffers cannot
                # alias any output (labels vs param-shaped outputs) make XLA
                # warn "donated buffers were not usable" at lowering — the
                # expected cost of rank-aware donation, not a bug
                import warnings
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore",
                        message="Some donated buffers were not usable")
                    new_state, self._ring, metrics = fn(
                        state, self._ring, batch, rng)
            else:
                new_state, self._ring, metrics = fn(
                    state, self._ring, batch, rng)
        # Steady state performs ZERO host syncs: the only wait is the ring
        # flush every flush_steps, which on the multi-device CPU runtime
        # also bounds async dispatch depth (hundreds of un-retired step
        # programs can starve its collective rendezvous — 7-of-8 threads
        # arrive, the runtime aborts). Real TPU runtimes bound their own
        # launch queue, so only the CPU mesh pays the flush wait.
        self._steps_since_flush += 1
        if self._throttled and self._steps_since_flush >= self.flush_steps():
            self.flush_metrics()
        return new_state, metrics

    def flush_metrics(self) -> Optional[Dict[str, np.ndarray]]:
        """Fetch the device metrics ring: ONE counted host sync
        (``trainer.flush``) retiring every step dispatched since the last
        flush. Returns ``{"loss": (flush_steps,) float32, "step": int32}``
        host values, or None when no step has run. Callers that want
        periodic loss telemetry WITHOUT per-step syncs read it here."""
        if self._ring is None:
            return None
        vals = obssyncs.device_get(self._ring, "trainer.flush")
        self._steps_since_flush = 0
        return {k: np.asarray(v) for k, v in vals.items()}

    def eval_step(self, state, batch, rng) -> jax.Array:
        if self._state_shardings is None:
            raise RuntimeError("call init() before eval_step()")
        if self._eval_step is None:
            self._eval_step = jax.jit(
                lambda params, batch, rng: self.loss_fn(params, batch, rng))
        with self.mesh:
            return self._eval_step(state["params"], batch, rng)

    # -- telemetry ---------------------------------------------------------
    def _estimate_flops(self, state, batch, rng) -> float:
        """FLOPs of one compiled train step via XLA cost analysis.

        Reuses the already-jitted step (lower+compile hits the jit cache, so
        no second compile) and runs at most once per trainer — the result is
        memoized in ``_flops_per_step``. Returns 0.0 when the backend offers
        no cost model; the MFU gauges are simply skipped then.
        """
        try:
            fn = next(iter(self._train_steps.values()))
            ring = self._ring if self._ring is not None else self._init_ring()
            with self.mesh:
                cost = (fn.lower(state, ring, batch, rng)
                        .compile().cost_analysis())
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else {}
            return float(cost.get("flops", 0.0)) if cost else 0.0
        except Exception as e:
            get_logger("parallel.trainer").debug(
                "step cost analysis unavailable (%s: %s)",
                type(e).__name__, e)
            return 0.0

    def _finish_epoch_telemetry(self, steps: int, rows: int,
                                wall_s: float) -> None:
        """End-of-epoch gauges + ``train.fit`` event (throughput, MFU)."""
        eps = rows / max(wall_s, 1e-9)
        obsmetrics.gauge("trainer.examples_per_sec").set(eps)
        mfu = None
        if self._flops_per_step:
            achieved = (self._flops_per_step * steps
                        / max(wall_s, 1e-9) / 1e12)
            obsmetrics.gauge("trainer.achieved_tflops").set(achieved)
            # MFU only means something against a real accelerator peak;
            # on the CPU mesh the v5e denominator would be noise
            if not is_cpu_mesh(self.mesh):
                peak = float(mmlconfig.get("observability.peak_tflops"))
                if peak > 0:
                    mfu = achieved / peak
                    obsmetrics.gauge("trainer.mfu").set(mfu)
        if obsevents.events_enabled():
            fields = dict(steps=steps, rows=rows, wall_s=round(wall_s, 6),
                          examples_per_sec=round(eps, 3))
            if mfu is not None:
                fields["mfu"] = round(mfu, 4)
            obsevents.emit("event", "train.fit", **fields)

    # -- data --------------------------------------------------------------
    def put_batch(self, batch: Dict[str, np.ndarray]) -> Dict[str, jax.Array]:
        with self.mesh:
            return shard_batch(self.mesh, batch, seq_axis=self.seq_axis)

    def fit(self, state, batches: Iterable[Dict[str, np.ndarray]],
            rng: Optional[jax.Array] = None,
            log_every: int = 0,
            log_fn: Callable[[int, float], None] = None,
            prefetch: Optional[int] = None,
            collect_losses: bool = True) -> Tuple[Any, list]:
        """Drive an epoch of host batches through the sharded step.

        ``batches`` is any iterable of host-batch dicts — a list, a
        generator, or a streaming ``mmlspark_tpu.data.Dataset`` (its
        iterator is built here; pass the Dataset itself, not ``.iter()``,
        unless mid-epoch state must be owned by the caller).

        Host->HBM transfer is double-buffered: a DevicePrefetcher thread
        assembles host batches ahead of the loop, and each ``device_put``
        dispatches asynchronously on this thread so the transfer overlaps
        the still-running step (depth from ``prefetch`` or the
        ``runtime.prefetch_depth`` config key). ``log_every``>0 emits
        step/loss/examples-per-sec through the MetricLogger (or a custom
        ``log_fn(step, loss)``). ``collect_losses=False`` skips
        materializing the per-step loss history (it costs a device stack +
        transfer at the end) and returns an empty list.
        """
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        if isinstance(batches, Dataset):
            batches = batches.iter()
        losses = []
        metric_log = (MetricLogger(every=log_every)
                      if log_every and log_fn is None else None)
        # telemetry is decided ONCE per fit, outside the step loop — with
        # observability.* unset the loop body pays a single falsy check per
        # step (no clock read, no histogram, no device sync)
        telemetry = obsmetrics.metrics_enabled() or obsevents.events_enabled()
        steps = rows_total = 0
        if telemetry:
            step_hist = obsmetrics.histogram("trainer.step_time_seconds")
            t_start = t_prev = obsevents.perf()
            sync_t0 = obssyncs.total()
            # ring flushes are amortized bookkeeping, not per-step stalls:
            # the steady-state gauge excludes them (tracked by site delta)
            flush_t0 = obsmetrics.counter(
                "observability.sync_points.trainer.flush").value
        prefetcher = DevicePrefetcher(batches, self.put_batch, depth=prefetch)
        # liveness: one beat per dispatched step — a wedged collective or
        # stuck input shows up as this heartbeat going silent, and the
        # watchdog dumps every thread's stack while the hang is live
        hb = _watchdog.register("trainer.fit")
        try:
            for i, batch in enumerate(prefetcher):
                hb.beat()
                rows = next(iter(batch.values())).shape[0] if batch else 0
                # streaming batches are single-use device transfers, so the
                # step donates them (no input double-buffering in HBM)
                state, metrics = self.train_step(state, batch, rng,
                                                 donate_batch=True)
                losses.append(metrics["loss"])  # device scalar: no per-step sync
                if telemetry:
                    # dispatch-to-dispatch wall time: non-blocking (the loss
                    # stays a device scalar; JAX dispatch is async, so this
                    # tracks the pipeline's sustained rate, not device
                    # latency of one step)
                    now = obsevents.perf()
                    step_hist.observe(now - t_prev)
                    t_prev = now
                    steps += 1
                    rows_total += rows
                    if self._flops_per_step is None:
                        self._flops_per_step = self._estimate_flops(
                            state, batch, rng)
                if log_fn is not None and log_every and i % log_every == 0:
                    log_fn(i, float(losses[-1]))
                elif metric_log is not None:  # cadence handled inside (no
                    metric_log(i, {"loss": losses[-1]},  # sync off-cadence)
                               batch_rows=rows)
        finally:
            hb.close()          # deregister: a finished fit never "stalls"
            prefetcher.close()  # stops the producer if we exited early
            closer = getattr(batches, "close", None)
            if callable(closer):  # pipeline iterators own decode pools
                closer()
        if telemetry and steps:
            # the ROADMAP item-4 scoreboard, sampled BEFORE the epoch-end
            # wait below and net of ring flushes: steady-state stepping
            # itself performs zero host round trips, and this gauge reads
            # exactly that (0.0) instead of charging the epoch's amortized
            # bookkeeping to the step loop
            flush_delta = (obsmetrics.counter(
                "observability.sync_points.trainer.flush").value - flush_t0)
            obsmetrics.gauge("train.sync_points_per_step").set(
                max(0.0, obssyncs.total() - sync_t0 - flush_delta) / steps)
            # one sync per EPOCH (the exit paths below all wait on the last
            # loss anyway) so throughput covers completed device work, not
            # just async dispatch
            obssyncs.block_until_ready(losses[-1],
                                       "trainer.epoch_telemetry")
            self._finish_epoch_telemetry(steps, rows_total,
                                         obsevents.perf() - t_start)
        if not losses:
            return state, []
        if not collect_losses:
            obssyncs.block_until_ready(losses[-1], "trainer.fit_exit")
            return state, []
        # one stack + one transfer: device_get on a LIST of device scalars
        # fetches each individually — a round trip per step on remote chips
        with self.mesh:
            stacked = jnp.stack(losses)
        return state, [float(l) for l in np.asarray(
            obssyncs.device_get(stacked, "trainer.collect_losses"))]
