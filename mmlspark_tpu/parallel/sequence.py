"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The long-context capability the task brief makes first-class (the reference
predates attention entirely — SURVEY.md §5 "Long-context / sequence
parallelism: absent"), built the TPU way:

- **Ring attention** (`ring_attention`): Q stays put; K/V blocks rotate
  around the ``seq`` mesh axis via ``ppermute`` (one ICI hop per step) while
  each device accumulates its queries' attention with the online-softmax
  (flash) recurrence. Peak memory per device is O(L_local^2) and the K/V
  transfer overlaps compute on real ICI. Blockwise-parallel-transformer /
  RingAttention pattern (Liu et al. 2023), PAPERS.md.
- **Ulysses** (`ulysses_attention`): two ``all_to_all``s swap the sharded
  axis sequence<->heads so each device computes FULL-sequence attention for
  a head subset. Cheaper at moderate L (2 collectives instead of S ppermute
  steps) but requires heads % seq_axis_size == 0.

Both are drop-in ``attention_fn`` implementations for
``models/zoo/transformer.py`` and differentiate through ``shard_map``
(ppermute's transpose is the reverse ppermute, so the backward pass is a
ring in the opposite direction — no custom VJP needed).

Shapes follow the framework convention (B, L, H, D) with L sharded over the
``seq`` axis at the boundary (``sharding.batch_sharding(seq_axis=...)``).
"""
from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from mmlspark_tpu.parallel.sharding import (
    active_batch_axes, shard_map_compat as shard_map,
)


def full_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   causal: bool = True,
                   use_flash: str = "auto") -> jnp.ndarray:
    """Single-device attention (B, L, H, D).

    On an accelerator backend with block-divisible shapes this runs the
    fused Pallas flash kernel (``ops/pallas_attention.py``) — the L x L
    score matrix never touches HBM. Everything else (CPU lanes, ragged
    lengths like ViT's 197 tokens) takes the jnp reference below: matmuls
    in the input dtype (bf16 tiles the MXU); scores, softmax and the
    output accumulation in fp32, cast back once at the end.
    ``use_flash``: "auto" | "never" (reference path, used by the parity
    tests themselves).
    """
    if use_flash == "auto" and jax.default_backend() != "cpu":
        from mmlspark_tpu.ops import pallas_attention
        if pallas_attention.supports(q.shape):
            return pallas_attention.flash_attention(q, k, v, causal=causal)
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("blhd,bkhd->bhlk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        L, K = s.shape[-2], s.shape[-1]
        mask = jnp.arange(K)[None, :] > jnp.arange(L)[:, None]
        s = jnp.where(mask, -jnp.inf, s)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhlk,bkhd->blhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-device body: accumulate over rotating K/V blocks (online softmax)."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    scale = 1.0 / np.sqrt(D)
    q_pos = my_idx * Lq + jnp.arange(Lq)                   # global positions

    def step(carry, i):
        # accumulators (o, m, l) live in fp32 — bf16 rounding would compound
        # across ring steps (flash-attention convention); k/v stay in the
        # input dtype so the rotating transfers and matmuls remain cheap
        o, m, l, k, v = carry
        owner = (my_idx - i) % axis_size                   # whose block is here
        s = jnp.einsum("blhd,bkhd->bhlk", q, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = owner * Lk + jnp.arange(Lk)
            mask = k_pos[None, :] > q_pos[:, None]          # (Lq, Lk)
            s = jnp.where(mask[None, None], -jnp.inf, s)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp(-inf - -inf) guard: fully-masked rows keep m=-inf, p=0
        p = jnp.exp(s - jnp.where(jnp.isinf(m_new), 0.0, m_new)[..., None])
        p = jnp.where(jnp.isinf(s), 0.0, p)
        corr = jnp.exp(jnp.where(jnp.isinf(m), 0.0, m)
                       - jnp.where(jnp.isinf(m_new), 0.0, m_new))
        corr = jnp.where(jnp.isinf(m), 0.0, corr)
        l_new = l * corr + p.sum(axis=-1)
        o_new = (o * corr[..., None]
                 + jnp.einsum("bhlk,bkhd->bhld", p.astype(v.dtype), v,
                              preferred_element_type=jnp.float32))
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        return (o_new, m_new, l_new, k, v), None

    o0 = jnp.zeros((B, H, Lq, D), jnp.float32)
    m0 = jnp.full((B, H, Lq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Lq), jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-30)[..., None]             # (B,H,Lq,D)
    return out.astype(q.dtype).transpose(0, 2, 1, 3)       # (B,Lq,H,D)


def _qkv_spec(mesh: Mesh, seq_axis: str, n_heads: int) -> P:
    """(B, L, H, D) spec: batch over data axes, L over seq, and — when the
    head count divides it — H over ``tensor``, so a tp x sp mesh keeps the
    tensor-sharded qkv projections sharded through attention instead of
    all-gathering and redundantly computing every head per tensor shard."""
    batch = active_batch_axes(mesh)
    t = mesh.shape.get("tensor", 1)
    head = "tensor" if t > 1 and n_heads % t == 0 else None
    return P(batch, seq_axis, head, None)  # lint: allow-spec (shard_map spec)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, seq_axis: str = "seq",
                   causal: bool = True) -> jnp.ndarray:
    """Context-parallel attention; (B, L, H, D) with L sharded over seq_axis."""
    if mesh.shape.get(seq_axis, 1) == 1:
        return full_attention(q, k, v, causal)
    spec = _qkv_spec(mesh, seq_axis, q.shape[2])
    fn = shard_map(
        partial(_ring_attention_local, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


# ---------------------------------------------------------------------------
def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """all_to_all seq<->heads, full-sequence attention on a head subset."""
    a2a = partial(jax.lax.all_to_all, axis_name=axis_name, tiled=True)
    # (B, L/s, H, D) -> (B, L, H/s, D): gather sequence, scatter heads
    q, k, v = (a2a(x, split_axis=2, concat_axis=1) for x in (q, k, v))
    o = full_attention(q, k, v, causal)
    # back: (B, L, H/s, D) -> (B, L/s, H, D)
    return a2a(o, split_axis=1, concat_axis=2)


def ulysses_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                      mesh: Mesh, seq_axis: str = "seq",
                      causal: bool = True) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    Requires n_heads divisible by the seq axis size.
    """
    s = mesh.shape.get(seq_axis, 1)
    if s == 1:
        return full_attention(q, k, v, causal)
    spec = _qkv_spec(mesh, seq_axis, q.shape[2])
    # the all_to_all splits the LOCAL head count (after any tensor sharding)
    local_heads = q.shape[2] // (mesh.shape.get("tensor", 1)
                                 if spec[2] == "tensor" else 1)
    if local_heads % s:
        raise ValueError(
            f"ulysses needs per-shard heads ({local_heads}) divisible by "
            f"|{seq_axis}|={s}")
    fn = shard_map(
        partial(_ulysses_local, axis_name=seq_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return fn(q, k, v)


def make_attention_fn(mesh: Optional[Mesh], impl: str = "auto",
                      seq_axis: str = "seq"):
    """attention_fn factory for TransformerLM: 'full' | 'ring' | 'ulysses' |
    'auto' (ring when the mesh has a non-trivial seq axis)."""
    if impl == "auto":
        impl = ("ring" if mesh is not None
                and mesh.shape.get(seq_axis, 1) > 1 else "full")
    if impl == "full":
        return full_attention
    if impl == "ring":
        return partial(ring_attention, mesh=mesh, seq_axis=seq_axis)
    if impl == "ulysses":
        return partial(ulysses_attention, mesh=mesh, seq_axis=seq_axis)
    raise ValueError(f"unknown attention impl {impl!r}")
