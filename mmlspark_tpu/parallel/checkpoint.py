"""Mid-training checkpoint / resume for sharded train state.

The reference has NO mid-training checkpointing (SURVEY.md §5: CNTK owns it
internally; the framework only persists fitted models). Here it is a
first-class capability: the sharded state pytree (params + optimizer state +
step) saves through orbax — each host writes its own shards, restore places
shards directly onto the mesh via the trainer's NamedShardings, so neither
direction ever materializes the full state on one host.

Usage::

    ckpt = TrainCheckpointer(dir, max_to_keep=3)
    state, resumed = ckpt.restore_or_init(trainer, init_params_fn)
    start_step = ckpt.latest_step() or 0
    for step, batch in enumerate(batches, start=start_step + 1):
        state, metrics = trainer.train_step(state, trainer.put_batch(batch), rng)
        ckpt.maybe_save(state, every=100, step=step)
    ckpt.save(state, wait=True)

Elastic restart = rerun the same program: ``restore_or_init`` picks up the
latest step and training continues bit-identically (fold_in(step) keys).
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from mmlspark_tpu.observability import events as obsevents
from mmlspark_tpu.observability import metrics as obsmetrics
from mmlspark_tpu.observability import syncs
from mmlspark_tpu.observability.spans import span
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.utils.logging import get_logger

_LOG = get_logger("parallel.checkpoint")


class TrainCheckpointer:
    """Orbax-backed checkpoint manager for DistributedTrainer state."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        self._closed = False
        self._mgr = self._make_manager()

    def _make_manager(self):
        return self._ocp.CheckpointManager(
            self.directory,
            options=self._ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep, create=True))

    # -- write --------------------------------------------------------------
    def save(self, state: Any, step: Optional[int] = None,
             wait: bool = False) -> int:
        """Save (async by default); step defaults to state['step']."""
        if step is None:
            step = int(syncs.device_get(state["step"], "checkpoint.step"))
        stale = os.path.join(self.directory, str(step))
        if os.path.isdir(stale):
            # A dead run's in-flight save for this step landed after restore
            # listed the committed steps (or tore mid-write). The state being
            # written now was regenerated deterministically from an older
            # checkpoint, so it supersedes the leftover; orbax refuses to
            # overwrite, so clobber it and refresh the cached step list.
            _LOG.warning("save(%d): removing stale step dir %s", step, stale)
            shutil.rmtree(stale)
            self.reload()
        with span("checkpoint", "save", step=step):
            fault_site("checkpoint.save")
            self._mgr.save(step, args=self._ocp.args.StandardSave(state))
            fault_site("checkpoint.save.commit")
            if wait:
                self._mgr.wait_until_finished()
        obsmetrics.counter("checkpoint.saves").inc()
        return step

    def wait(self) -> None:
        """Block until any in-flight async save has committed."""
        self._mgr.wait_until_finished()

    def maybe_save(self, state: Any, every: int, step: int,
                   wait: bool = False) -> Optional[int]:
        """Save when ``step`` (the HOST loop counter — passing it avoids a
        device sync per step) is a positive multiple of ``every``."""
        if every > 0 and step > 0 and step % every == 0:
            return self.save(state, step=step, wait=wait)
        return None

    # -- run metadata -------------------------------------------------------
    # Small facts about HOW the run draws its data (e.g. the batch-order
    # mode) that a resume must replay identically but that don't belong in
    # the sharded state pytree. JSON sidecar next to the checkpoints;
    # process 0 writes, every process reads.
    _META = "mmlspark_meta.json"

    def put_meta(self, **meta: Any) -> None:
        if jax.process_index() != 0:
            return
        path = os.path.join(self.directory, self._META)
        data = self.get_meta()
        data.update(meta)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)

    def get_meta(self) -> Dict[str, Any]:
        # Only a MISSING sidecar means "no metadata" (pre-sidecar
        # checkpoints); any other read/parse failure must surface — callers
        # pin resume behavior on this, so silently returning {} would let a
        # transient storage error flip the batch-order mode.
        try:
            with open(os.path.join(self.directory, self._META)) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    # -- input-pipeline state ------------------------------------------------
    # The streaming pipeline's iterator snapshot (data/pipeline.py
    # state_dict: file cursor, shuffle block, batch boundary) saves NEXT TO
    # each checkpoint step so ResilientTrainLoop.run_dataset resumes the
    # batch stream mid-epoch bit-identically. Unlike the run-metadata
    # sidecar above, this is PER PROCESS — each host's shard cursor
    # differs — and per step, so a quarantined step falls back to the
    # older step's matching snapshot. Same atomic tmp+replace discipline.
    _DATA_STATE_RE = re.compile(r"data_state-(\d+)\.p\d+\.json$")

    def _data_state_path(self, step: int) -> str:
        return os.path.join(
            self.directory,
            f"data_state-{step}.p{jax.process_index()}.json")

    def put_data_state(self, step: int, state: Dict[str, Any]) -> str:
        """Persist an input-pipeline ``state_dict`` for ``step`` (call it
        just BEFORE ``save(step)``: an orphan snapshot for an uncommitted
        step is harmless, a committed step without its snapshot loses
        mid-epoch resume). The snapshot is wrapped with a sha256 of its
        canonical JSON, verified at :meth:`get_data_state` — the sidecar
        gets the same torn-write/bitrot protection orbax gives the params.
        Returns the written path."""
        path = self._data_state_path(step)
        body = json.dumps(state, sort_keys=True)
        wrapper = {"sha256": hashlib.sha256(body.encode()).hexdigest(),
                   "state": state}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(wrapper, f, sort_keys=True)
        os.replace(tmp, path)
        self._gc_data_state(keep_step=step)
        return path

    def get_data_state(self, step: int) -> Optional[Dict[str, Any]]:
        """This process's pipeline snapshot for ``step``, or None when the
        checkpoint predates the streaming pipeline (params-only resume) OR
        the sidecar fails integrity checks. A corrupt/mismatched sidecar
        is QUARANTINED (renamed aside, like a corrupt checkpoint step) —
        resuming the stream from its beginning costs duplicate batches;
        resuming from a silently corrupt cursor is wrong forever."""
        path = self._data_state_path(step)
        try:
            with open(path) as f:
                payload = json.load(f)
        except FileNotFoundError:
            return None
        except ValueError:
            return self._quarantine_data_state(path, "unparseable JSON")
        if not (isinstance(payload, dict) and "sha256" in payload
                and "state" in payload):
            # pre-sha256 sidecar (older writer): no integrity field to
            # check, load it as-is for backward compatibility
            return payload if isinstance(payload, dict) else \
                self._quarantine_data_state(path, "not a JSON object")
        body = json.dumps(payload["state"], sort_keys=True)
        actual = hashlib.sha256(body.encode()).hexdigest()
        if actual != payload["sha256"]:
            return self._quarantine_data_state(
                path, f"sha256 {actual[:12]} != recorded "
                f"{str(payload['sha256'])[:12]}")
        return payload["state"]

    def _quarantine_data_state(self, path: str,
                               why: str) -> None:
        quarantined = os.path.join(
            os.path.dirname(path), "corrupt-" + os.path.basename(path))
        _LOG.warning("data-state sidecar %s failed verification (%s); "
                     "quarantined to %s — the input stream restarts",
                     path, why, quarantined)
        try:
            os.replace(path, quarantined)
        except OSError as e:   # already moved by a concurrent reader
            _LOG.debug("data-state quarantine skipped (%s)", e)
        obsmetrics.counter("checkpoint.data_state_quarantined").inc()
        if obsevents.events_enabled():
            obsevents.emit("event", "checkpoint.data_state_quarantine",
                           path=path, reason=why)
        return None

    def _gc_data_state(self, keep_step: int) -> None:
        """Drop snapshots for steps orbax has pruned (max_to_keep); the
        step being written now may not be committed yet, so it is always
        kept explicitly."""
        keep = set(self.all_steps()) | {keep_step}
        for name in os.listdir(self.directory):
            m = self._DATA_STATE_RE.match(name)
            if m and int(m.group(1)) not in keep:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError as e:  # another process may GC concurrently
                    _LOG.debug("data-state GC skipped %s (%s)", name, e)

    # -- read ---------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def restore(self, trainer, init_params_fn: Callable[[], Any],
                step: Optional[int] = None) -> Any:
        """Restore ``step`` (default latest) directly into the trainer's
        shardings; no full-state host copy."""
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        with span("checkpoint", "restore", step=step):
            fault_site("checkpoint.restore")
            abstract, shardings = trainer.abstract_state(init_params_fn)
            target = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
                abstract, shardings)
            with trainer.mesh:
                restored = self._mgr.restore(
                    step, args=self._ocp.args.StandardRestore(target))
        obsmetrics.counter("checkpoint.restores").inc()
        return restored

    def restore_or_init(self, trainer, init_params_fn: Callable[[], Any]
                        ) -> Tuple[Any, bool]:
        """(state, resumed): latest checkpoint if one exists, else fresh init.

        Either way the trainer's sharding spec is established, so
        ``train_step`` works immediately after.
        """
        if self.latest_step() is None:
            return trainer.init(init_params_fn), False
        return self.restore(trainer, init_params_fn), True

    # -- recovery -----------------------------------------------------------
    def quarantine_step(self, step: int) -> str:
        """Move a bad step's directory aside (``corrupt-<step>``: non-numeric
        name, so orbax no longer lists it) and reload the manager so
        ``latest_step``/``all_steps`` reflect the removal. The data is
        preserved for forensics, not deleted. Returns the quarantine path."""
        src = os.path.join(self.directory, str(step))
        dst = os.path.join(self.directory, f"corrupt-{step}")
        n = 0
        while os.path.exists(dst):
            n += 1
            dst = os.path.join(self.directory, f"corrupt-{step}.{n}")
        if os.path.exists(src):
            os.rename(src, dst)
        else:
            _LOG.warning("quarantine_step(%d): %s does not exist", step, src)
        self.reload()
        obsmetrics.counter("checkpoint.quarantines").inc()
        if obsevents.events_enabled():
            obsevents.emit("event", "checkpoint.quarantine", step=step,
                           path=dst)
        return dst

    def reload(self) -> None:
        """Recreate the orbax manager, picking up external directory changes
        (quarantined steps, another process's saves). The manager caches its
        step list, so mutations behind its back need this."""
        try:
            self._mgr.close()
        except Exception as e:
            # a wedged manager must not block recovery; the replacement
            # manager supersedes it either way
            _LOG.warning("reload: closing old manager failed (%s: %s)",
                         type(e).__name__, e)
        self._mgr = self._make_manager()
        self._closed = False

    def close(self) -> None:
        """Idempotent close. A second call is a no-op; the FIRST call still
        surfaces async-save errors from ``wait_until_finished`` (a failed
        background save must not vanish into interpreter shutdown), while
        the manager is released either way."""
        if self._closed:
            return
        self._closed = True
        try:
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()
