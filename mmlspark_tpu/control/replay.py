"""Counterfactual policy replay over recorded autopilot telemetry.

The autopilot's decision core is a PURE function — ``decide(signals,
policy, state)`` reads no clock and does no I/O — and every live run
records both sides of it: ``autopilot_signals`` events carry the policy
(once) and the full per-tick signal payload, ``autopilot`` events carry
the decisions made. That makes recorded runs replayable offline:

- **Fidelity**: replaying the recorded signals under the recorded
  policy reproduces the recorded decision list *byte for byte* (the
  events satellite's replay-sufficiency promise, now checked by a
  tool instead of asserted in a docstring).
- **Counterfactuals**: replaying the same signals under CANDIDATE
  policies shows what each would have decided, scored by a first-order
  outcome model (below) — turning the 18 hand-tuned ``autopilot.*``
  thresholds into measurable choices. ``mmlspark-tpu autopilot replay``
  prints the ranked comparison.

The counterfactual outcome model is deliberately simple and fully
deterministic: it does NOT re-simulate the fleet. Recorded per-tick shed
deltas and SLO burn are discounted by the capacity ratio
``recorded_live / virtual_live``, where ``virtual_live`` walks the
candidate's actuated scale decisions (so a policy that scales up earlier
is credited with proportionally less shed, one that never scales keeps
the recorded pain). Shift/admission decisions only count against the
action budget. This is a threshold-tuning instrument — rank candidates,
then canary the winner — not a simulator.
"""
from __future__ import annotations

import json
from dataclasses import fields as _dc_fields
from typing import Any, Dict, List, Optional, Sequence

from mmlspark_tpu.control.autopilot import (
    AutopilotPolicy, AutopilotState, advance_state, decide,
)
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("control.replay")

# decision-event fields added by ACTUATION (not by decide()): stripped
# when reconstructing the recorded decision list from events
_ACTUATION_KEYS = ("replica", "error")


def load_log(paths: Sequence[str]) -> Dict[str, Any]:
    """Parse one or more event JSONL files (per-host/per-pid sidecars
    merge naturally) into the replay inputs::

        {"policy": {field: value} | None,   # autopilot_signals/policy
         "ticks": [signals, ...],           # autopilot_signals/tick
         "decisions": [decision, ...]}      # autopilot events, normalized

    Events are merged across files and ordered by their wall-clock
    ``ts`` (stable for ties, so one file replays in write order).
    Unparseable lines are skipped with a warning — a sidecar truncated
    by a kill must not sink the whole replay.
    """
    rows: List[Dict[str, Any]] = []
    for path in paths:
        with open(path, "r", encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    e = json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("%s:%d: skipping unparseable line",
                                   path, lineno)
                    continue
                if isinstance(e, dict) and e.get("type") in (
                        "autopilot", "autopilot_signals"):
                    rows.append(e)
    rows.sort(key=lambda e: float(e.get("ts", 0.0)))
    policy: Optional[Dict[str, Any]] = None
    ticks: List[Dict[str, Any]] = []
    decisions: List[Dict[str, Any]] = []
    for e in rows:
        if e["type"] == "autopilot_signals":
            if e.get("name") == "policy" and policy is None:
                policy = {k: v for k, v in e.items()
                          if k not in ("ts", "type", "name")}
            elif e.get("name") == "tick":
                sig = e.get("signals")
                if isinstance(sig, dict):
                    ticks.append(sig)
        else:
            d = {k: v for k, v in e.items()
                 if k not in ("ts", "type") + _ACTUATION_KEYS}
            d["action"] = d.pop("name")
            decisions.append(d)
    return {"policy": policy, "ticks": ticks, "decisions": decisions}


def policy_from_fields(fields: Dict[str, Any],
                       overrides: Optional[Dict[str, Any]] = None
                       ) -> AutopilotPolicy:
    """Rebuild an :class:`AutopilotPolicy` from a recorded policy event
    (or any field dict), with candidate ``overrides`` applied on top.
    Unknown keys are rejected — a typo'd override must not silently
    replay the recorded threshold."""
    known = {f.name for f in _dc_fields(AutopilotPolicy)}
    vals: Dict[str, Any] = {k: v for k, v in (fields or {}).items()
                            if k in known}
    for k, v in (overrides or {}).items():
        if k not in known:
            raise ValueError(f"unknown policy field {k!r} "
                             f"(known: {sorted(known)})")
        vals[k] = v
    for name in ("min_replicas", "max_replicas", "hbm_limit_bytes",
                 "max_actions_per_window"):
        if name in vals:
            vals[name] = int(vals[name])
    return AutopilotPolicy(**vals)


def parse_overrides(spec: str) -> Dict[str, float]:
    """``"scale_up_queue=2,scale_cooldown_s=10"`` -> field dict (values
    parsed as JSON numbers/bools where possible, strings otherwise)."""
    out: Dict[str, Any] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"override {part!r} is not key=value")
        k, v = part.split("=", 1)
        try:
            out[k.strip()] = json.loads(v.strip())
        except json.JSONDecodeError:
            out[k.strip()] = v.strip()
    return out


def replay_decisions(ticks: Sequence[Dict[str, Any]],
                     policy: AutopilotPolicy) -> List[Dict[str, Any]]:
    """Run the pure decision core over the recorded signal frames under
    ``policy`` on the recorded (virtual) clock. Because the recorded
    frames already embed what the fleet did, replaying the RECORDED
    policy reproduces the recorded decision list exactly."""
    state = AutopilotState()
    out: List[Dict[str, Any]] = []
    for sig in ticks:
        ds = decide(sig, policy, state)
        advance_state(state, ds, sig, window_s=policy.window_s)
        out.extend(ds)
    return out


def fidelity_check(recorded: Sequence[Dict[str, Any]],
                   replayed: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Byte-identical comparison of the recorded vs replayed decision
    lists (canonical ``json.dumps(..., sort_keys=True)`` per decision).
    Returns ``{identical, recorded, replayed, first_diff}``."""
    a = [json.dumps(d, sort_keys=True, default=str) for d in recorded]
    b = [json.dumps(d, sort_keys=True, default=str) for d in replayed]
    first_diff = None
    for i, (x, y) in enumerate(zip(a, b)):
        if x != y:
            first_diff = {"index": i, "recorded": x, "replayed": y}
            break
    if first_diff is None and len(a) != len(b):
        i = min(len(a), len(b))
        first_diff = {"index": i,
                      "recorded": a[i] if i < len(a) else None,
                      "replayed": b[i] if i < len(b) else None}
    return {"identical": a == b, "recorded": len(a), "replayed": len(b),
            "first_diff": first_diff}


def _live_and_shed(ticks: Sequence[Dict[str, Any]]):
    """Per-tick (recorded_live, shed_delta, burn_fast) from the signal
    frames: live = ready replicas, shed deltas from the per-replica
    monotone shed counters."""
    prev_shed: Dict[str, float] = {}
    rows = []
    for sig in ticks:
        reps = sig.get("replicas") or {}
        live = sum(1 for r in reps.values() if r.get("ready"))
        delta = 0.0
        for name, r in reps.items():
            s = float(r.get("shed", 0.0))
            delta += max(0.0, s - prev_shed.get(name, 0.0))
            prev_shed[name] = s
        burn = float((sig.get("slo") or {}).get("burn_fast", 0.0))
        rows.append((live, delta, burn))
    return rows


def score_policy(ticks: Sequence[Dict[str, Any]],
                 policy: AutopilotPolicy) -> Dict[str, Any]:
    """Counterfactual outcome of ``policy`` over the recorded frames.

    ``virtual_live`` starts at the first frame's recorded live count and
    walks the candidate's actuated scale decisions (bounded by the
    candidate's own min/max); each tick's recorded shed delta and SLO
    burn are discounted by ``recorded_live / virtual_live`` — the
    capacity the candidate would have had relative to what the recorded
    run actually had. Lower is better on every score."""
    state = AutopilotState()
    rows = _live_and_shed(ticks)
    virtual = rows[0][0] if rows else 0
    cf_shed = 0.0
    cf_burn = 0.0
    actions = 0
    scale_ups = scale_downs = 0
    for sig, (live, shed_delta, burn) in zip(ticks, rows):
        ds = decide(sig, policy, state)
        advance_state(state, ds, sig, window_s=policy.window_s)
        for d in ds:
            if d.get("suppressed"):
                continue
            actions += 1
            if d["action"] == "scale_up" and virtual < policy.max_replicas:
                virtual += 1
                scale_ups += 1
            elif d["action"] == "scale_down" \
                    and virtual > policy.min_replicas:
                virtual -= 1
                scale_downs += 1
        ratio = live / max(1, virtual)
        cf_shed += shed_delta * ratio
        cf_burn += burn * ratio
    return {"shed": round(cf_shed, 4), "slo_burn": round(cf_burn, 4),
            "actions": actions, "scale_ups": scale_ups,
            "scale_downs": scale_downs,
            "final_virtual_replicas": virtual, "ticks": len(ticks)}


def rank_policies(ticks: Sequence[Dict[str, Any]],
                  candidates: Dict[str, AutopilotPolicy]
                  ) -> List[Dict[str, Any]]:
    """Score every candidate and rank best-first: least counterfactual
    shed, then least SLO burn, then fewest actuations (a quieter
    controller wins ties)."""
    scored = []
    for name, pol in candidates.items():
        s = score_policy(ticks, pol)
        s["policy"] = name
        scored.append(s)
    scored.sort(key=lambda s: (s["shed"], s["slo_burn"], s["actions"],
                               s["policy"]))
    for i, s in enumerate(scored, 1):
        s["rank"] = i
    return scored


def format_ranking(ranked: Sequence[Dict[str, Any]],
                   fidelity: Optional[Dict[str, Any]] = None) -> str:
    """Human-readable ranked comparison (the CLI's output)."""
    lines = []
    if fidelity is not None:
        mark = "OK" if fidelity["identical"] else "MISMATCH"
        lines.append(
            f"fidelity: {mark} — recorded policy replays "
            f"{fidelity['replayed']}/{fidelity['recorded']} decisions "
            f"byte-identical={fidelity['identical']}")
        if fidelity["first_diff"] is not None:
            fd = fidelity["first_diff"]
            lines.append(f"  first diff at decision {fd['index']}:")
            lines.append(f"    recorded: {fd['recorded']}")
            lines.append(f"    replayed: {fd['replayed']}")
    head = (f"{'rank':>4}  {'policy':<24} {'cf_shed':>10} "
            f"{'cf_slo_burn':>12} {'actions':>8} {'up/down':>8}")
    lines.append(head)
    lines.append("-" * len(head))
    for s in ranked:
        lines.append(
            f"{s['rank']:>4}  {s['policy']:<24} {s['shed']:>10.2f} "
            f"{s['slo_burn']:>12.2f} {s['actions']:>8} "
            f"{s['scale_ups']}/{s['scale_downs']:>4}")
    return "\n".join(lines)
