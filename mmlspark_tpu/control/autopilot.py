"""Autopilot: the SLO-driven control loop over the serving fleet.

PR 10 built the sensors (burn-rate :class:`SloEngine`, replica-labeled
:class:`FleetScraper`, HBM :class:`MemoryLedger`) and PRs 7/11 the levers
(``Router.set_weight``, ``Fleet`` replica lifecycle,
``WeightedFairAdmission``, rollout abort). This module closes the loop,
after the design of Google's Autopilot (Rzadca et al., EuroSys 2020) and
the SRE Workbook's multi-window burn-rate alerts:

- **Sense**: one scrape + one SLO observation per evaluation tick, on an
  injectable clock.
- **Decide**: :func:`decide` is a PURE function of ``(signals, policy,
  state)`` — no clock reads, no I/O, no mutation — so every decision is
  unit-testable as a table row and replayable from its event payload.
- **Actuate** five levers: per-replica traffic shift (ramp
  ``Router.set_weight`` down on an error-rate outlier, back on
  recovery), replica scale up/down through ``Fleet`` (bounded by
  ``autopilot.{min,max}_replicas`` and HBM headroom), adaptive admission
  (tighten/relax the ``WeightedFairAdmission`` fleet quota under
  fast-window burn), the rollout guard (abort ``Fleet.rollout`` when
  the canary burns), and — PR 20 — the elastic mesh
  (``Fleet.reshard``: widen the tensor axis under HBM-ledger pressure,
  narrow it when queue depth wants replicas the scale lever can no
  longer add; ``autopilot.reshard_*`` keys).
- **Hysteresis is part of the decision core**, not an afterthought:
  separate up/down thresholds per lever, per-lever cooldowns keyed so a
  reversal (A -> B -> A) cannot happen inside one cooldown window, and a
  rolling max-actions budget. The chaos scenario asserts no-flap from
  the event stream alone.

Every decision — actuated OR considered-but-suppressed (cooldown,
actuation-budget window, bounds veto) — is emitted as an ``autopilot``
event with enough payload to replay it, counted in the metrics registry,
and surfaced by ``mmlspark-tpu report`` / ``top``. See
docs/AUTOPILOT.md for the signal -> lever matrix and tuning runbook.
"""
from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, fields as _dc_fields
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("control.autopilot")


@dataclass(frozen=True)
class AutopilotPolicy:
    """Every threshold the decision function reads, in one frozen value.

    Defaults come from the ``autopilot.*`` config keys
    (:meth:`from_config`); tests construct policies directly. Up/down
    thresholds are deliberately separated per lever — the gap between
    them is the hysteresis band that keeps the controller from chasing
    noise."""

    tick_s: float = 5.0
    min_replicas: int = 1
    max_replicas: int = 8
    hbm_limit_bytes: int = 0
    scale_up_queue: float = 4.0
    scale_down_queue: float = 0.0
    scale_cooldown_s: float = 25.0
    shift_error_rate: float = 0.5
    shift_recover_rate: float = 0.05
    shift_step: float = 0.5
    shift_cooldown_s: float = 20.0
    admission_factor: float = 0.5
    admission_floor_frac: float = 0.25
    admission_relax_burn: float = 1.0
    admission_cooldown_s: float = 25.0
    # fifth lever — elastic mesh: the target placements ('' disables the
    # direction). ``reshard_wide`` is the wider-tensor-axis shape taken
    # under HBM-ledger pressure (per-chip bytes shrink as the tensor axis
    # grows); ``reshard_narrow`` the narrower shape taken when queue
    # depth wants replicas the scale lever can no longer add. Both
    # directions share one cooldown key, so wide -> narrow -> wide
    # cannot flap inside a window (structural hysteresis, like scale).
    reshard_wide: str = ""
    reshard_narrow: str = ""
    reshard_hbm_frac: float = 0.85
    reshard_cooldown_s: float = 60.0
    window_s: float = 120.0
    max_actions_per_window: int = 8

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("autopilot.min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("autopilot.max_replicas must be >= min")
        if not (0.0 < self.shift_step <= 1.0):
            raise ValueError("autopilot.shift_step must be in (0, 1]")
        if self.shift_recover_rate > self.shift_error_rate:
            raise ValueError("shift_recover_rate must be <= "
                             "shift_error_rate (hysteresis band)")
        if self.scale_down_queue > self.scale_up_queue:
            raise ValueError("scale_down_queue must be <= scale_up_queue "
                             "(hysteresis band)")
        if not (0.0 < self.admission_factor < 1.0):
            raise ValueError("admission_factor must be in (0, 1)")
        if not (0.0 < self.reshard_hbm_frac <= 1.0):
            raise ValueError(
                "reshard_hbm_frac must be in (0, 1]")
        if self.reshard_wide and self.reshard_wide == self.reshard_narrow:
            raise ValueError(
                "reshard_wide and reshard_narrow must name DIFFERENT "
                "shapes (the gap is the hysteresis band)")

    @classmethod
    def from_config(cls, **overrides) -> "AutopilotPolicy":
        kw = {f.name: f.type for f in _dc_fields(cls)}
        vals: Dict[str, Any] = {}
        for name in kw:
            vals[name] = mmlconfig.get(f"autopilot.{name}")
        vals.update(overrides)
        for name in ("min_replicas", "max_replicas", "hbm_limit_bytes",
                     "max_actions_per_window"):
            vals[name] = int(vals[name])
        return cls(**vals)


class AutopilotState:
    """The controller's memory between ticks: previous per-replica
    counters (decisions key on DELTAS, not lifetime totals), last-action
    timestamps per cooldown key, and the rolling actuation deque the
    max-actions window counts over. Mutated only by
    :func:`advance_state` / :meth:`Autopilot._apply` — :func:`decide`
    just reads it."""

    def __init__(self):
        self.prev: Dict[str, Dict[str, float]] = {}
        self.last_action: Dict[str, float] = {}
        self.actions: Deque[Tuple[float, str]] = deque()
        self.ticks = 0


def cooldown_key(lever: str, target: str) -> str:
    """Cooldown bucket for one decision. Scale, admission, and reshard
    are fleet-level (up and down — or wide and narrow — share one key so
    one direction cannot chase the other inside the cooldown); shift and
    everything replica-scoped key per target for the same reason — both
    directions of a lever share its key, which is what makes the no-flap
    property structural."""
    return lever if lever in ("scale", "admission", "reshard") else \
        f"{lever}:{target}"


def _last_name(names) -> str:
    """Deterministic scale-down victim: the highest-numbered replica
    (numeric-aware so ``r10`` sorts after ``r2``)."""
    return max(names, key=lambda n: (len(n), n))


def decide(signals: Dict[str, Any], policy: AutopilotPolicy,
           state: AutopilotState) -> List[Dict[str, Any]]:
    """The pure decision core: ``(signals, policy, state) -> decisions``.

    ``signals`` is the dict :func:`fleet_signals` builds (see there for
    the schema); ``state`` is read, never written. Each decision dict
    carries ``lever``/``action``/``target``/``t``/``suppressed``/
    ``reason`` plus the numeric inputs that produced it — the replay
    payload the events satellite requires. Suppressed decisions are the
    considered-but-held ones: cooldown, actuation-budget window, or a
    bounds veto (max replicas, HBM headroom, admission floor)."""
    now = float(signals["now"])
    decisions: List[Dict[str, Any]] = []
    budget = {"used": sum(1 for (t, _) in state.actions
                          if now - t < policy.window_s)}

    def push(lever: str, action: str, target: str, reason: str,
             cd_s: float, **payload) -> None:
        d: Dict[str, Any] = {"lever": lever, "action": action,
                             "target": target, "t": now,
                             "suppressed": False, "reason": reason}
        d.update(payload)
        key = cooldown_key(lever, target)
        last = state.last_action.get(key)
        if last is not None and now - last < cd_s:
            d["suppressed"] = True
            d["reason"] = (f"cooldown:{key} ({now - last:.0f}s of "
                           f"{cd_s:.0f}s; wanted: {reason})")
        elif budget["used"] >= policy.max_actions_per_window:
            d["suppressed"] = True
            d["reason"] = (f"window:{budget['used']}/"
                           f"{policy.max_actions_per_window} actions in "
                           f"{policy.window_s:.0f}s (wanted: {reason})")
        else:
            budget["used"] += 1
        decisions.append(d)

    def veto(lever: str, action: str, target: str, reason: str,
             **payload) -> None:
        decisions.append({"lever": lever, "action": action,
                          "target": target, "t": now, "suppressed": True,
                          "reason": reason, **payload})

    replicas: Dict[str, Dict[str, Any]] = signals.get("replicas", {})
    slo = signals.get("slo", {})
    burning = bool(slo.get("burning"))
    burn_fast = float(slo.get("burn_fast", 0.0))

    # -- lever 1: traffic shift (per replica, sorted for determinism) ----
    for name in sorted(replicas):
        r = replicas[name]
        prev = state.prev.get(name)
        if prev is None:
            continue        # first sighting: no deltas to judge yet
        dfail = max(0.0, float(r.get("failed", 0.0))
                    - float(prev.get("failed", 0.0)))
        dgood = max(0.0, float(r.get("completed", 0.0))
                    - float(prev.get("completed", 0.0)))
        total = dfail + dgood
        err = dfail / total if total > 0 else 0.0
        weight = float(r.get("weight", 0.0))
        ready = bool(r.get("ready"))
        unhealthy = (not ready) or (total > 0
                                    and err >= policy.shift_error_rate)
        recovered = ready and (total == 0
                               or err <= policy.shift_recover_rate)
        if unhealthy and weight > 0.0:
            new_w = round(max(0.0, weight - policy.shift_step), 6)
            reason = "replica not ready" if not ready else \
                (f"error rate {err:.2f} >= "
                 f"{policy.shift_error_rate:.2f}")
            push("shift", "shift_down", name, reason,
                 policy.shift_cooldown_s, weight=weight,
                 new_weight=new_w, error_rate=round(err, 4))
        elif recovered and weight < 1.0:
            new_w = round(min(1.0, weight + policy.shift_step), 6)
            push("shift", "shift_up", name,
                 f"recovered (error rate {err:.2f} <= "
                 f"{policy.shift_recover_rate:.2f})",
                 policy.shift_cooldown_s, weight=weight,
                 new_weight=new_w, error_rate=round(err, 4))

    # -- lever 2: replica scale ------------------------------------------
    ready_names = sorted(n for n, r in replicas.items() if r.get("ready"))
    live = len(ready_names)
    mean_q = (sum(float(replicas[n].get("queue_depth", 0.0))
                  for n in ready_names) / live) if live else 0.0
    hbm = float(signals.get("memory", {}).get("total_bytes", 0.0))
    scale_payload = dict(live=live, queue_mean=round(mean_q, 3),
                         burn_fast=round(burn_fast, 3),
                         hbm_bytes=int(hbm))

    want_up, up_reason = False, ""
    if live < policy.min_replicas:
        want_up, up_reason = True, (f"live {live} < min "
                                    f"{policy.min_replicas}")
    elif mean_q >= policy.scale_up_queue:
        want_up, up_reason = True, (f"mean queue {mean_q:.1f} >= "
                                    f"{policy.scale_up_queue:.1f}")
    elif burning and mean_q >= max(1.0, policy.scale_up_queue / 2.0):
        want_up, up_reason = True, (f"slo burning (fast {burn_fast:.1f})"
                                    f" with mean queue {mean_q:.1f}")
    if want_up:
        total_reps = len(replicas)
        projected = hbm + (hbm / live if live else 0.0)
        if total_reps >= policy.max_replicas:
            veto("scale", "scale_up", "",
                 f"bounds:max_replicas ({total_reps} >= "
                 f"{policy.max_replicas}; wanted: {up_reason})",
                 **scale_payload)
        elif policy.hbm_limit_bytes > 0 \
                and projected > policy.hbm_limit_bytes:
            veto("scale", "scale_up", "",
                 f"bounds:hbm (projected {int(projected)} > limit "
                 f"{policy.hbm_limit_bytes}; wanted: {up_reason})",
                 **scale_payload)
        else:
            push("scale", "scale_up", "", up_reason,
                 policy.scale_cooldown_s, **scale_payload)
    elif (not burning) and live > policy.min_replicas \
            and mean_q <= policy.scale_down_queue:
        target = _last_name(ready_names)
        push("scale", "scale_down", target,
             f"idle (mean queue {mean_q:.1f} <= "
             f"{policy.scale_down_queue:.1f}, live {live} > min "
             f"{policy.min_replicas})",
             policy.scale_cooldown_s, **scale_payload)

    # -- lever 3: adaptive admission -------------------------------------
    adm = signals.get("admission")
    if adm:
        cap = int(adm.get("capacity_rows", 0))
        baseline = int(adm.get("baseline_rows", cap)) or cap
        floor = max(1, int(baseline * policy.admission_floor_frac))
        adm_payload = dict(capacity_rows=cap, baseline_rows=baseline,
                           burn_fast=round(burn_fast, 3))
        if burning:
            new_cap = max(floor, int(cap * policy.admission_factor))
            if new_cap < cap:
                push("admission", "admission_tighten", "",
                     f"slo burning (fast {burn_fast:.1f})",
                     policy.admission_cooldown_s,
                     new_capacity=new_cap, **adm_payload)
            else:
                veto("admission", "admission_tighten", "",
                     f"bounds:floor (capacity {cap} already at floor "
                     f"{floor})", **adm_payload)
        elif cap < baseline and burn_fast <= policy.admission_relax_burn:
            new_cap = min(baseline,
                          max(cap + 1,
                              int(round(cap / policy.admission_factor))))
            push("admission", "admission_relax", "",
                 f"burn {burn_fast:.2f} <= "
                 f"{policy.admission_relax_burn:.2f}, capacity {cap} < "
                 f"baseline {baseline}",
                 policy.admission_cooldown_s,
                 new_capacity=new_cap, **adm_payload)

    # -- lever 5: elastic mesh (Fleet.reshard) ---------------------------
    # Wide under HBM-ledger pressure (a wider tensor axis shrinks every
    # chip's resident shard); narrow when queue depth wants replicas the
    # scale lever is vetoed from adding. The two triggers are disjoint
    # pressure regimes and both directions share the "reshard" cooldown
    # key, so the controller cannot oscillate placements.
    cur_shape = str(signals.get("mesh", {}).get("shape", ""))
    if policy.reshard_wide or policy.reshard_narrow:
        total_reps = len(replicas)
        mesh_payload = dict(mesh_shape=cur_shape,
                            hbm_bytes=int(hbm), live=live,
                            queue_mean=round(mean_q, 3))
        hbm_pressure = (policy.hbm_limit_bytes > 0
                        and hbm >= policy.reshard_hbm_frac
                        * policy.hbm_limit_bytes)
        queue_pressure = (want_up
                          and total_reps >= policy.max_replicas)
        if hbm_pressure and policy.reshard_wide:
            reason = (f"hbm {int(hbm)} >= {policy.reshard_hbm_frac:.2f}"
                      f" * limit {policy.hbm_limit_bytes}")
            if cur_shape == policy.reshard_wide:
                veto("reshard", "reshard_wide", policy.reshard_wide,
                     f"bounds:at_target ({cur_shape!r}; wanted: "
                     f"{reason})", **mesh_payload)
            else:
                push("reshard", "reshard_wide", policy.reshard_wide,
                     reason, policy.reshard_cooldown_s, **mesh_payload)
        elif queue_pressure and policy.reshard_narrow:
            reason = (f"queue wants replicas past max "
                      f"{policy.max_replicas} (mean queue {mean_q:.1f};"
                      f" wanted: {up_reason})")
            if cur_shape == policy.reshard_narrow:
                veto("reshard", "reshard_narrow", policy.reshard_narrow,
                     f"bounds:at_target ({cur_shape!r}; wanted: "
                     f"{reason})", **mesh_payload)
            else:
                push("reshard", "reshard_narrow", policy.reshard_narrow,
                     reason, policy.reshard_cooldown_s, **mesh_payload)

    return decisions


def fleet_signals(snap: Dict[str, Any],
                  slo_status: List[Dict[str, Any]],
                  router_stats: Dict[str, Any],
                  now: float, *,
                  admission: Optional[Dict[str, int]] = None,
                  mesh_shape: Optional[str] = None
                  ) -> Dict[str, Any]:
    """Distill one scraper snapshot + SLO observation + router stats into
    the flat signal dict :func:`decide` consumes::

        {"now": t,
         "replicas": {name: {ready, weight, queue_depth, inflight,
                             completed, failed, shed}},
         "slo": {"burning": bool, "breaching": bool, "burn_fast": max},
         "memory": {"total_bytes": int},
         "admission": {"capacity_rows": int, "baseline_rows": int},
         "mesh": {"shape": "4x2"}}

    Readiness comes from the scrape (health truth), weight from the
    router (rotation truth) — the two sides of "is this replica taking
    traffic"."""
    rstats = (router_stats or {}).get("replicas", {})
    reps: Dict[str, Dict[str, Any]] = {}
    for name, one in (snap.get("replicas") or {}).items():
        st = one.get("stats") or {}
        reps[name] = {
            "ready": bool(one.get("ready")),
            "live": bool(one.get("live")),
            "weight": float(rstats.get(name, {}).get("weight", 0.0)),
            "queue_depth": float(st.get("queue_depth", 0.0)),
            "inflight": float(st.get("inflight", 0.0)),
            "completed": float(st.get("completed", 0.0)),
            "failed": float(st.get("failed", 0.0)),
            "shed": float(st.get("shed", 0.0)),
        }
    status = slo_status or []
    sig: Dict[str, Any] = {
        "now": float(now),
        "replicas": reps,
        "slo": {
            "burning": any(s.get("burning") or s.get("breaching")
                           for s in status),
            "breaching": any(s.get("breaching") for s in status),
            "burn_fast": max((float(s.get("burn_fast", 0.0))
                              for s in status), default=0.0),
        },
        "memory": {"total_bytes": float(
            (snap.get("memory") or {}).get("total_bytes", 0.0))},
    }
    if admission:
        sig["admission"] = dict(admission)
    if mesh_shape is not None:
        sig["mesh"] = {"shape": str(mesh_shape)}
    return sig


def advance_state(state: AutopilotState, decisions: List[Dict[str, Any]],
                  signals: Dict[str, Any], *,
                  window_s: float) -> None:
    """Commit one tick into ``state``: record actuated decisions against
    their cooldown keys and the rolling budget window, refresh the
    per-replica counter baseline, trim the window. Split out of the
    class so table tests can run decide/advance cycles with no fleet."""
    now = float(signals["now"])
    for d in decisions:
        if d.get("suppressed"):
            continue
        key = cooldown_key(d["lever"], d.get("target", ""))
        state.last_action[key] = now
        state.actions.append((now, key))
    while state.actions and now - state.actions[0][0] >= window_s:
        state.actions.popleft()
    state.prev = {
        name: {"completed": float(r.get("completed", 0.0)),
               "failed": float(r.get("failed", 0.0))}
        for name, r in (signals.get("replicas") or {}).items()}
    state.ticks += 1


class Autopilot:
    """The closed loop: scrape -> SLO observe -> :func:`decide` ->
    actuate + emit, once per tick.

    ``fleet`` is an in-process :class:`~mmlspark_tpu.serve.fleet.Fleet`
    or a process-backed :class:`~mmlspark_tpu.serve.fleet.ProcessFleet`
    (selected by ``autopilot.scale_backend`` in the CLI — same actuator
    surface, real OS workers); scraper/engine/policy/clock are injectable (the chaos scenario and
    tests drive :meth:`tick` on a virtual clock; ``serve --autopilot``
    uses :meth:`start`'s daemon thread). Every decision is emitted as an
    ``autopilot`` event whether actuated or suppressed; actuation
    failures never kill the loop — they mark the decision's event with
    ``error`` and the controller re-evaluates next tick."""

    def __init__(self, fleet, *,
                 scraper=None, engine=None,
                 policy: Optional[AutopilotPolicy] = None,
                 clock: Optional[Callable[[], float]] = None):
        from mmlspark_tpu.observability.aggregate import FleetScraper
        from mmlspark_tpu.observability.slo import SloEngine
        self.fleet = fleet
        self.router = fleet.router
        self.clock = clock if clock is not None else events.wall
        self.scraper = scraper if scraper is not None else \
            FleetScraper(fleet, clock=self.clock)
        self.engine = engine if engine is not None else \
            SloEngine(clock=self.clock)
        self.policy = policy if policy is not None else \
            AutopilotPolicy.from_config()
        self.state = AutopilotState()
        self._counts = {"actions": 0, "suppressed": 0, "errors": 0}
        self._by_action: Dict[str, int] = {}
        self._recent: Deque[Dict[str, Any]] = deque(maxlen=8)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._policy_emitted = False

    def _emit_signals(self, sig: Dict[str, Any]) -> None:
        """Record the replay feed: one ``autopilot_signals``/``policy``
        event per run (the thresholds the recorded decisions were made
        under) and one ``autopilot_signals``/``tick`` event per tick
        (the FULL signal payload :func:`decide` saw). A distinct event
        type from ``autopilot`` on purpose — decision consumers (the
        chaos no-flap check, the report's decision counts) must not see
        signal frames. Together they make ``mmlspark-tpu autopilot
        replay`` exact: decide() is pure, so policy + signals reproduce
        the decision list byte for byte."""
        if not events.recording_enabled():
            return
        if not self._policy_emitted:
            self._policy_emitted = True
            events.emit("autopilot_signals", "policy",
                        **{f.name: getattr(self.policy, f.name)
                           for f in _dc_fields(AutopilotPolicy)})
        events.emit("autopilot_signals", "tick", signals=sig)

    # -- one evaluation tick ---------------------------------------------
    def tick(self) -> List[Dict[str, Any]]:
        """Sense, decide, actuate, record. Returns this tick's decision
        list (actuated and suppressed) for callers that replay or
        assert on it directly."""
        snap = self.scraper.scrape()
        status = self.engine.observe(self.scraper.slo_sample(snap))
        fairness = self.router.fairness
        sig = fleet_signals(
            snap, status, self.router.stats(), float(self.clock()),
            admission={"capacity_rows": int(fairness.capacity_rows),
                       "baseline_rows": int(getattr(
                           fairness, "baseline_rows",
                           fairness.capacity_rows))},
            mesh_shape=str(getattr(self.fleet, "mesh_shape", "")))
        self._emit_signals(sig)
        decisions = decide(sig, self.policy, self.state)
        for d in decisions:
            if not d["suppressed"]:
                self._actuate(d)
            self._record(d)
        advance_state(self.state, decisions, sig,
                      window_s=self.policy.window_s)
        return decisions

    def _actuate(self, d: Dict[str, Any]) -> None:
        try:
            action = d["action"]
            if action in ("shift_down", "shift_up"):
                self.router.set_weight(d["target"], d["new_weight"])
            elif action == "scale_up":
                d["replica"] = self.fleet.scale_up()
            elif action == "scale_down":
                self.fleet.scale_down(d["target"])
            elif action == "admission_tighten" \
                    or action == "admission_relax":
                self.router.fairness.set_capacity(d["new_capacity"])
            elif action in ("reshard_wide", "reshard_narrow"):
                d["report"] = self.fleet.reshard(d["target"])
            else:  # pragma: no cover - decide() and _actuate in lockstep
                raise ValueError(f"unknown action {action!r}")
        except Exception as e:
            # a failed actuation must not kill the loop: the decision
            # stays visible (with the error), cooldown still applies so
            # the controller does not hammer a broken lever, and the
            # next tick re-senses reality
            logger.error("autopilot actuation %s failed: %s",
                         d["action"], e)
            d["error"] = f"{type(e).__name__}: {e}"
            self._counts["errors"] += 1

    def _record(self, d: Dict[str, Any]) -> None:
        kind = "suppressed" if d["suppressed"] else "actions"
        self._counts[kind] += 1
        self._by_action[d["action"]] = \
            self._by_action.get(d["action"], 0) + 1
        self._recent.append(d)
        if metrics.metrics_enabled():
            metrics.counter(f"autopilot.{kind}").inc()
            metrics.counter(f"autopilot.{d['action']}").inc()
        if events.recording_enabled():
            events.emit("autopilot", d["action"],
                        **{k: v for k, v in d.items() if k != "action"})

    # -- rollout guard ----------------------------------------------------
    def rollout_guard(self, replica: str) -> Optional[str]:
        """``Fleet.rollout(guard=...)`` hook: re-sense AFTER the canary
        took traffic on the new version; a burning SLO returns the abort
        reason (rollout raises ``RolloutAborted``), a healthy one
        returns None. Both outcomes are recorded — the hold shows up as
        a suppressed ``rollout_abort`` decision, so a post-mortem can
        see the guard looked and chose not to fire."""
        snap = self.scraper.scrape()
        status = self.engine.observe(self.scraper.slo_sample(snap))
        burning = any(s.get("burning") or s.get("breaching")
                      for s in status)
        burn = max((float(s.get("burn_fast", 0.0)) for s in status),
                   default=0.0)
        reason = (f"canary SLO burning (fast burn {burn:.1f})"
                  if burning else
                  f"hold:canary-healthy (fast burn {burn:.1f})")
        self._record({"lever": "rollout", "action": "rollout_abort",
                      "target": replica, "t": float(self.clock()),
                      "suppressed": not burning, "reason": reason,
                      "burn_fast": round(burn, 3)})
        return reason if burning else None

    # -- observability ----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The ``top`` panel / report section source: tick + decision
        counters plus the most recent decisions (action, target,
        suppressed, reason)."""
        return {
            "ticks": self.state.ticks,
            "actions": self._counts["actions"],
            "suppressed": self._counts["suppressed"],
            "errors": self._counts["errors"],
            "by_action": dict(sorted(self._by_action.items())),
            "recent": [{"action": d["action"],
                        "target": d.get("target", ""),
                        "suppressed": bool(d["suppressed"]),
                        "reason": d.get("reason", "")}
                       for d in self._recent],
        }

    # -- background loop --------------------------------------------------
    def start(self) -> None:
        """Tick on a daemon thread every ``policy.tick_s`` until
        :meth:`stop` (the ``serve --autopilot`` mode)."""
        if self._thread is not None:
            return

        def loop() -> None:
            while not self._stop.wait(self.policy.tick_s):
                try:
                    self.tick()
                except Exception:  # pragma: no cover - defensive
                    logger.exception("autopilot tick failed")

        self._stop.clear()
        self._thread = threading.Thread(
            target=loop, name="mmlspark-tpu-autopilot", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
