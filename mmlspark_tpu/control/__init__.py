"""Control plane: close the loop from fleet signals to fleet levers.

The observability stack (scraper, SLO engine, HBM ledger) senses;
``serve/`` exposes the levers (router weights, replica lifecycle,
admission quotas, rollout abort); this package is the part that DECIDES.
See :mod:`mmlspark_tpu.control.autopilot`.
"""
from mmlspark_tpu.control.autopilot import (  # noqa: F401
    Autopilot, AutopilotPolicy, AutopilotState, decide, fleet_signals,
)

__all__ = ["Autopilot", "AutopilotPolicy", "AutopilotState", "decide",
           "fleet_signals"]
