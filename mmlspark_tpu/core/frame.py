"""Frame: a partitioned, columnar, host-resident dataset that streams to TPU.

This is the JVM-free re-expression of the Spark DataFrame surface the
reference's ML layer needs (select/withColumn/na.drop/cache/repartition —
see SURVEY.md §7 "Hard parts"). Partitions are host-local dicts of numpy
arrays; ops are eager per-partition (no Catalyst rebuild). Device hand-off
happens via :meth:`Frame.batches` and ``mmlspark_tpu.parallel.data.device_put_sharded``
which stream stacked batches into sharded ``jax.Array``s — the TPU-native
equivalent of the reference's broadcast + ``mapPartitions`` minibatch loop
(``cntk-model/src/main/scala/CNTKModel.scala:215-221``).

Storage conventions per DType:
  numeric  -> 1-D ndarray of the numpy dtype
  STRING   -> 1-D object ndarray of str (None for missing)
  VECTOR   -> 2-D ndarray (n_rows, dim); float32 canonical, uint8 permitted
              (the raw-bytes wire format: 1/4 the host->HBM traffic, cast
              on device). Storage dtype is UNIFORM across partitions —
              Frame.__init__ enforces it, so consumers that cast must cast
              (uint8 arithmetic wraps) but never see mixed batches.
  IMAGE    -> 1-D object ndarray of schema.ImageValue
  BINARY   -> 1-D object ndarray of bytes
  TOKENS   -> 1-D object ndarray of list[str]
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.schema import ColumnSchema, DType, Schema, SchemaError

Partition = Dict[str, np.ndarray]


def _infer_dtype(arr: np.ndarray) -> Tuple[DType, Optional[int]]:
    if arr.ndim == 2:
        return DType.VECTOR, int(arr.shape[1])
    if arr.dtype == np.bool_:
        return DType.BOOL, None
    if np.issubdtype(arr.dtype, np.integer):
        return (DType.INT32 if arr.dtype.itemsize <= 4 else DType.INT64), None
    if np.issubdtype(arr.dtype, np.floating):
        return (DType.FLOAT32 if arr.dtype.itemsize <= 4 else DType.FLOAT64), None
    # object arrays: inspect first non-null
    for v in arr:
        if v is None:
            continue
        if isinstance(v, str):
            return DType.STRING, None
        if isinstance(v, (bool, np.bool_)):
            return DType.BOOL, None
        if isinstance(v, (int, float, np.number)):
            return DType.FLOAT64, None
        if isinstance(v, (bytes, bytearray)):
            return DType.BINARY, None
        if isinstance(v, list):
            return DType.TOKENS, None
        from mmlspark_tpu.core.schema import ImageValue
        if isinstance(v, ImageValue):
            return DType.IMAGE, None
        if isinstance(v, np.ndarray):
            return DType.VECTOR, int(v.shape[0])
    return DType.STRING, None


def _normalize(values: Any, dtype: Optional[DType] = None) -> Tuple[np.ndarray, DType, Optional[int]]:
    """Coerce a python sequence / ndarray into canonical column storage."""
    if isinstance(values, np.ndarray) and values.dtype != np.object_:
        arr = values
    else:
        lst = list(values)
        if lst and isinstance(lst[0], np.ndarray) and dtype in (None, DType.VECTOR):
            all_u8 = all(isinstance(v, np.ndarray) and v.dtype == np.uint8
                         for v in lst)
            elem = np.uint8 if all_u8 else np.float32
            arr = np.stack([np.asarray(v, dtype=elem) for v in lst])
        else:
            numeric = (bool(lst)
                       and any(v is not None for v in lst)
                       and all(v is None or isinstance(v, (int, float, bool, np.number))
                               for v in lst))
            has_none = any(v is None for v in lst)
            try:
                if dtype is not None and dtype.is_numeric:
                    if has_none:  # missing numeric -> NaN (na_drop can remove it)
                        arr = np.asarray([np.nan if v is None else v for v in lst],
                                         dtype=np.float64)
                    else:
                        arr = np.asarray(lst, dtype=dtype.numpy_dtype)
                elif dtype is None and numeric:
                    if has_none:
                        arr = np.asarray([np.nan if v is None else v for v in lst],
                                         dtype=np.float64)
                    else:
                        arr = np.asarray(lst)
                else:
                    raise ValueError
            except (ValueError, TypeError):
                arr = np.empty(len(lst), dtype=np.object_)
                for i, v in enumerate(lst):
                    arr[i] = v
    if dtype is None:
        dtype, dim = _infer_dtype(arr)
    else:
        dim = int(arr.shape[1]) if arr.ndim == 2 else None
    if dtype == DType.VECTOR and arr.ndim == 2 and arr.dtype != np.float32:
        # uint8 vectors keep their storage dtype: the raw-bytes wire format
        # crosses host->HBM at 1/4 the fp32 size and consumers (JaxModel,
        # the fused preprocess) cast on device. Everything else stores as
        # the canonical float32.
        if arr.dtype != np.uint8:
            arr = arr.astype(np.float32)
    elif dtype.is_numeric and arr.dtype != dtype.numpy_dtype and arr.dtype != np.object_:
        if (np.issubdtype(arr.dtype, np.floating)
                and np.issubdtype(dtype.numpy_dtype, np.integer)
                and np.isnan(arr).any()):
            dtype = DType.FLOAT64  # NaN is unrepresentable in ints; stay float
            arr = arr.astype(np.float64)
        else:
            arr = arr.astype(dtype.numpy_dtype)
    return arr, dtype, dim


class Frame:
    """Partitioned columnar dataset. Immutable-by-convention: ops return new Frames."""

    def __init__(self, schema: Schema, partitions: List[Partition]):
        self.schema = schema
        # own the list (not its dicts): _unify_vector_dtypes may replace
        # entries copy-on-write without touching a caller-shared list
        self.partitions = list(partitions) if partitions else [
            {c.name: _empty_column(c) for c in schema}]
        # memo for multi-partition column() concatenations (partitions are
        # immutable-by-convention, so the gather never goes stale)
        self._column_cache: Dict[str, np.ndarray] = {}
        for part in self.partitions:
            lens = {len(part[c.name]) for c in schema}
            if len(lens) > 1:
                raise SchemaError(f"ragged partition: column lengths {lens}")
        self._unify_vector_dtypes()

    def _unify_vector_dtypes(self) -> None:
        """One storage dtype per VECTOR column across ALL partitions.

        uint8 survives only when every non-empty partition agrees (the
        raw-bytes wire format); any divergence — a per-partition
        ``with_column`` that produced float rows somewhere, a union with a
        float frame — canonicalizes the whole column to float32. Without
        this, a batch's dtype would depend on which partitions it spans and
        a jitted consumer would silently retrace mid-stream. Empty
        partitions don't vote but are re-typed to match.
        """
        for c in self.schema:
            if c.dtype != DType.VECTOR:
                continue
            # Only dense 2-D ndarray storage participates: a VECTOR column
            # can also arrive as a 1-D object array or plain list
            # (list-of-lists input, duck-typed map_partitions output) which
            # astype cannot densify — those are left for the consumer-side
            # np.asarray, as before this pass existed.
            vals = [part[c.name] for part in self.partitions]
            dense_idx = [i for i, a in enumerate(vals)
                         if isinstance(a, np.ndarray) and a.ndim == 2
                         and a.dtype != np.object_]
            if not dense_idx:
                continue
            dts = {vals[i].dtype for i in dense_idx if len(vals[i])}
            if not dts:
                continue  # all-empty: keep dtypes (a filtered-to-empty
                # uint8 frame must not silently flip to float32)
            # uint8 survives only when EVERY partition is dense uint8;
            # object/ragged partitions break purity, so dense ones
            # canonicalize to float32
            target = (np.dtype(np.uint8)
                      if len(dense_idx) == len(vals)
                      and dts == {np.dtype(np.uint8)}
                      else np.dtype(np.float32))
            for i in dense_idx:
                if vals[i].dtype != target:
                    # copy-on-write: partition dicts may be shared with
                    # sibling frames that must keep their own storage
                    part = dict(self.partitions[i])
                    part[c.name] = vals[i].astype(target)
                    self.partitions[i] = part

    # -- constructors ------------------------------------------------------
    @staticmethod
    def from_dict(data: Dict[str, Any], num_partitions: int = 1,
                  schema: Optional[Schema] = None) -> "Frame":
        cols: Dict[str, np.ndarray] = {}
        schemas: List[ColumnSchema] = []
        for name, values in data.items():
            want = schema[name].dtype if schema is not None and name in schema else None
            arr, dtype, dim = _normalize(values, want)
            cols[name] = arr
            base = schema[name] if schema is not None and name in schema else None
            md = dict(base.metadata) if base else {}
            schemas.append(ColumnSchema(name, dtype, dim, md))
        n = len(next(iter(cols.values()))) if cols else 0
        frame = Frame(Schema(schemas), [cols])
        return frame.repartition(num_partitions) if num_partitions > 1 and n else frame

    @staticmethod
    def from_rows(rows: Sequence[Dict[str, Any]], num_partitions: int = 1) -> "Frame":
        if not rows:
            raise SchemaError("from_rows needs at least one row")
        names = list(rows[0].keys())
        return Frame.from_dict({n: [r[n] for r in rows] for n in names}, num_partitions)

    @staticmethod
    def concat(frames: Sequence["Frame"]) -> "Frame":
        if not frames:
            raise SchemaError("concat requires at least one frame")
        first = frames[0]
        for f in frames[1:]:
            if f.schema.names != first.schema.names:
                raise SchemaError(
                    f"concat schema mismatch: {f.schema.names} vs {first.schema.names}")
        parts = [p for f in frames for p in f.partitions]
        return Frame(first.schema, parts)

    # -- basic info --------------------------------------------------------
    @property
    def num_partitions(self) -> int:
        return len(self.partitions)

    def count(self) -> int:
        first = self.schema.names[0] if self.schema.names else None
        if first is None:
            return 0
        return sum(len(p[first]) for p in self.partitions)

    def __len__(self) -> int:
        return self.count()

    @property
    def columns(self) -> List[str]:
        return self.schema.names

    # -- column access -----------------------------------------------------
    def column(self, name: str) -> np.ndarray:
        """Concatenate one column across partitions (driver-side collect).
        Multi-partition gathers are memoized, so per-epoch consumers
        (``shuffled_batches``) pay the concatenation once per frame."""
        self.schema[name]
        arrs = [p[name] for p in self.partitions]
        if len(arrs) == 1:
            return arrs[0]
        cached = self._column_cache.get(name)
        if cached is None:
            cached = self._column_cache[name] = np.concatenate(arrs, axis=0)
        return cached

    def collect(self) -> Dict[str, np.ndarray]:
        return {n: self.column(n) for n in self.schema.names}

    def head(self, n: int = 5) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for part in self.partitions:
            take = min(n - len(out), len(part[self.schema.names[0]]))
            for i in range(take):
                out.append({name: part[name][i] for name in self.schema.names})
            if len(out) >= n:
                break
        return out

    # -- relational ops ----------------------------------------------------
    def select(self, *names: str) -> "Frame":
        names = list(names[0]) if len(names) == 1 and isinstance(names[0], (list, tuple)) else list(names)
        schema = self.schema.select(names)
        parts = [{n: p[n] for n in names} for p in self.partitions]
        return Frame(schema, parts)

    def drop(self, *names: str) -> "Frame":
        keep = [n for n in self.schema.names if n not in set(names)]
        return self.select(*keep)

    def rename(self, mapping: Dict[str, str]) -> "Frame":
        schema = Schema([c.renamed(mapping.get(c.name, c.name)) for c in self.schema])
        parts = [{mapping.get(n, n): p[n] for n in self.schema.names}
                 for p in self.partitions]
        return Frame(schema, parts)

    def with_column(self, col: ColumnSchema,
                    fn: Callable[[Partition], np.ndarray]) -> "Frame":
        """Add/replace a column; ``fn`` maps a partition dict to the new array."""
        # Two passes: normalize every partition first, then unify on ONE
        # dtype — otherwise a NaN appearing in only one partition would leave
        # the schema disagreeing with the other partitions' arrays.
        normalized = [_normalize(fn(p), col.dtype) for p in self.partitions]
        actuals = {a for _, a, _ in normalized}
        final_dtype = col.dtype
        final_dim = col.dim
        if len(actuals) == 1:
            only = next(iter(actuals))
            if only != col.dtype:
                final_dtype = only
        elif actuals and all(a.is_numeric for a in actuals):
            final_dtype = DType.FLOAT64
        if col.dtype == DType.VECTOR:
            dims = {d for _, _, d in normalized if d is not None}
            if final_dim is None and len(dims) == 1:
                final_dim = next(iter(dims))
            elif len(dims) > 1:
                raise SchemaError(
                    f"column {col.name!r}: inconsistent vector dims {dims}")
        schema = self.schema.add(
            ColumnSchema(col.name, final_dtype, final_dim, col.metadata))
        parts = []
        for p, (arr, actual, _) in zip(self.partitions, normalized):
            if final_dtype.is_numeric and arr.dtype != final_dtype.numpy_dtype:
                if arr.dtype == np.object_:
                    raise SchemaError(
                        f"column {col.name!r}: declared {final_dtype.value} but "
                        "produced non-numeric values")
                arr = arr.astype(final_dtype.numpy_dtype)
            q = dict(p)
            q[col.name] = arr
            parts.append(q)
        return Frame(schema, parts)

    def with_column_values(self, col: ColumnSchema, values: Any) -> "Frame":
        """Add/replace a column from a full-length array, split across partitions."""
        arr, actual, dim = _normalize(values, col.dtype)
        if col.dtype == DType.VECTOR and col.dim is None and dim is not None:
            col = ColumnSchema(col.name, col.dtype, dim, col.metadata)
        elif actual != col.dtype:
            col = ColumnSchema(col.name, actual, dim, col.metadata)
        if len(arr) != self.count():
            raise SchemaError(f"column length {len(arr)} != frame length {self.count()}")
        schema = self.schema.add(col)
        parts, off = [], 0
        for p in self.partitions:
            n = len(p[self.schema.names[0]]) if self.schema.names else len(arr)
            q = dict(p)
            q[col.name] = arr[off:off + n]
            parts.append(q)
            off += n
        return Frame(schema, parts)

    def with_metadata(self, name: str, **meta) -> "Frame":
        return Frame(self.schema.add(self.schema[name].with_meta(**meta)),
                     self.partitions)

    def map_partitions(self, schema: Schema,
                       fn: Callable[[Partition], Partition]) -> "Frame":
        return Frame(schema, [fn(dict(p)) for p in self.partitions])

    def filter(self, mask_fn: Callable[[Partition], np.ndarray]) -> "Frame":
        parts = []
        for p in self.partitions:
            mask = np.asarray(mask_fn(p), dtype=bool)
            parts.append({n: p[n][mask] for n in self.schema.names})
        return Frame(self.schema, parts)

    def random_split(self, weights: Sequence[float],
                     seed: int = 0) -> List["Frame"]:
        """Split rows into disjoint Frames with expected proportions
        ``weights`` — Spark's ``DataFrame.randomSplit``, which the
        reference's benchmark harness uses for its 60/40 train/test split
        (``VerifyTrainClassifier.scala:548-551``). Seeded per-row uniforms
        against the cumulative normalized weights, so every row lands in
        exactly one split and the same seed reproduces the partition."""
        w = np.asarray(list(weights), np.float64)
        if len(w) < 2 or not np.all(w > 0):   # catches NaN too
            raise ValueError(f"weights must be >=2 positive values, got "
                             f"{list(weights)}")
        edges = np.r_[0.0, np.cumsum(w) / w.sum()]
        edges[-1] = 1.0 + 1e-9          # a u of exactly 1.0 still lands
        first = self.schema.names[0]
        us = [np.random.default_rng((int(seed), i)).uniform(
                  size=len(p[first]))
              for i, p in enumerate(self.partitions)]
        out = []
        for lo, hi in zip(edges[:-1], edges[1:]):
            parts = [{n: p[n][(u >= lo) & (u < hi)]
                      for n in self.schema.names}
                     for p, u in zip(self.partitions, us)]
            out.append(Frame(self.schema, parts))
        return out

    def na_drop(self, cols: Optional[Sequence[str]] = None) -> "Frame":
        """Drop rows with None/NaN in any of ``cols`` (default: all columns)."""
        cols = list(cols) if cols is not None else self.schema.names

        def mask(p: Partition) -> np.ndarray:
            n = len(p[self.schema.names[0]])
            keep = np.ones(n, dtype=bool)
            for c in cols:
                arr = p[c]
                if arr.dtype == np.object_:
                    keep &= np.array([v is not None for v in arr])
                elif np.issubdtype(arr.dtype, np.floating):
                    if arr.ndim == 2:
                        keep &= ~np.isnan(arr).any(axis=1)
                    else:
                        keep &= ~np.isnan(arr)
            return keep
        return self.filter(mask)

    def distinct_values(self, col: str) -> List[Any]:
        seen, out = set(), []
        for p in self.partitions:
            for v in p[col]:
                key = v.item() if isinstance(v, np.generic) else v
                if isinstance(key, float) and math.isnan(key):
                    key = "__nan__"
                if key not in seen:
                    seen.add(key)
                    out.append(v)
        return out

    def union(self, other: "Frame") -> "Frame":
        if other.schema.names != self.schema.names:
            raise SchemaError("union requires identical column names")
        return Frame(self.schema, self.partitions + other.partitions)

    # -- partitioning (reference pipeline-stages/Repartition.scala) --------
    def repartition(self, n: int) -> "Frame":
        if n <= 0:
            raise SchemaError("repartition requires n >= 1")
        cols = self.collect()
        total = self.count()
        bounds = np.linspace(0, total, n + 1).astype(int)
        parts = [{name: arr[bounds[i]:bounds[i + 1]] for name, arr in cols.items()}
                 for i in range(n)]
        return Frame(self.schema, parts)

    def coalesce(self, n: int) -> "Frame":
        if n >= self.num_partitions:
            return self
        groups = np.array_split(np.arange(self.num_partitions), n)
        parts = []
        for g in groups:
            sub = [self.partitions[i] for i in g]
            parts.append({name: np.concatenate([p[name] for p in sub], axis=0)
                          for name in self.schema.names})
        return Frame(self.schema, parts)

    def process_shard(self, index: Optional[int] = None,
                      count: Optional[int] = None,
                      block_rows: Optional[int] = None) -> "Frame":
        """This process's row shard for multi-process training.

        Each host keeps only the rows its devices will hold — the
        TPU-native replacement for the reference's shared-filesystem
        hand-off where every MPI rank re-read the full dataset
        (``cntk-train/src/main/scala/DataConversion.scala:106-173``).
        Shards are balanced within one row/block, which is what the deep
        estimators' per-epoch quota assumes.

        Default (``block_rows=None``): contiguous split, rows
        ``[i*n/P, (i+1)*n/P)`` — simplest, order-preserving.

        ``block_rows=b``: block-cyclic — process ``i`` keeps row blocks
        ``i, i+P, i+2P, ...`` of size ``b``. With ``b`` = the per-process
        batch share (global batch / P), this is EXACTLY the set of rows a
        single-process run would place on this host's devices, so a
        multi-process DeviceEpochCache reproduces the single-process
        epoch layout bit for bit (the parity contract the multi-process
        trainer test pins).

        Defaults to this process's index/count from the live ``jax``
        process group; pass ``index``/``count`` to shard for another
        topology (e.g. writing per-host files ahead of a launch).
        """
        import jax
        i = jax.process_index() if index is None else int(index)
        p = jax.process_count() if count is None else int(count)
        if not 0 <= i < p:
            raise SchemaError(f"process_shard index {i} outside count {p}")
        n = self.count()
        cols = self.collect()
        if block_rows is None:
            bounds = np.linspace(0, n, p + 1).astype(int)
            idx = np.arange(int(bounds[i]), int(bounds[i + 1]))
        else:
            if block_rows <= 0:
                raise SchemaError(f"block_rows must be positive, "
                                  f"got {block_rows}")
            idx = np.nonzero((np.arange(n) // block_rows) % p == i)[0]
        return Frame(self.schema,
                     [{name: arr[idx] for name, arr in cols.items()}])

    def cache(self) -> "Frame":
        """Partitions are already materialized host arrays; kept for API parity
        with the reference's CheckpointData persist (CheckpointData.scala:31-70)."""
        return self

    def unpersist(self) -> "Frame":
        return self

    # -- device streaming --------------------------------------------------
    # Subclass hooks for batches(): DiskFrame swaps the batch assembler for
    # a must-copy variant and evicts a chunk's pages once it is consumed.
    _cat_batch = staticmethod(lambda arrs: _cat(arrs))

    def _partition_consumed(self, p: Partition) -> None:
        pass

    def batches(self, batch_size: int, cols: Optional[Sequence[str]] = None,
                drop_remainder: bool = False) -> Iterator[Dict[str, np.ndarray]]:
        """Yield stacked numpy minibatches across partition boundaries.

        The streaming analogue of the reference's buffered minibatch iterator
        (``CNTKModel.scala:50-104``) minus the per-element JVM->native copy sin:
        slices here are contiguous ndarray views handed to jax.device_put whole.
        """
        cols = list(cols) if cols is not None else self.schema.names
        buf: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        buffered = 0
        for p in self.partitions:
            n = len(p[cols[0]]) if cols else 0
            off = 0
            while off < n:
                take = min(batch_size - buffered, n - off)
                for c in cols:
                    buf[c].append(p[c][off:off + take])
                buffered += take
                off += take
                if buffered == batch_size:
                    yield {c: self._cat_batch(buf[c]) for c in cols}
                    buf = {c: [] for c in cols}
                    buffered = 0
            self._partition_consumed(p)
        if buffered and not drop_remainder:
            yield {c: self._cat_batch(buf[c]) for c in cols}

    def shuffled_batches(self, batch_size: int, cols: Optional[Sequence[str]] = None,
                         rng: Optional[np.random.Generator] = None,
                         drop_remainder: bool = False
                         ) -> Iterator[Dict[str, np.ndarray]]:
        """Minibatches in a fresh global row permutation (one per call).

        SGD learners need this: sequential ``batches`` on label- or
        time-ordered data trains each step on a biased slice. Partitions are
        host-resident and the column gather is memoized on the frame, so
        per-epoch calls pay only the permutation plus per-batch fancy
        indexing. Pass a persistent ``rng`` for reproducibility; the default
        draws fresh OS entropy per call.
        """
        rng = rng if rng is not None else np.random.default_rng()
        cols = list(cols) if cols is not None else self.schema.names
        arrs = {c: self.column(c) for c in cols}
        n = self.count()
        perm = rng.permutation(n)
        end = n - n % batch_size if drop_remainder else n
        for off in range(0, end, batch_size):
            idx = perm[off:off + batch_size]
            yield {c: arrs[c][idx] for c in cols}

    def __repr__(self) -> str:
        cols = ", ".join(f"{c.name}:{c.dtype.value}" for c in self.schema)
        return f"Frame[{cols}] rows={self.count()} partitions={self.num_partitions}"


def _cat(arrs: List[np.ndarray]) -> np.ndarray:
    return arrs[0] if len(arrs) == 1 else np.concatenate(arrs, axis=0)


def _empty_column(c: ColumnSchema) -> np.ndarray:
    if c.dtype == DType.VECTOR:
        return np.zeros((0, c.dim or 0), dtype=np.float32)
    return np.zeros(0, dtype=c.dtype.numpy_dtype)
