"""Typed metric contracts: the reference's MetricData re-expressed.

Reference ``core/contracts/src/main/scala/Metrics.scala:37-47`` defines
``MetricData.create/createTable`` — typed records that evaluators log.
Here the same contract: a scalar ``MetricValue`` and a ``MetricTable``
(named 2-D table, e.g. a confusion matrix or ROC curve), both renderable
to a Frame (the observable API) and loggable through the framework logger
(the reference logs accuracy/ROC tables at
``ComputeModelStatistics.scala:486-521``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence

import numpy as np


@dataclass(frozen=True)
class MetricValue:
    name: str
    value: float
    model_uid: str = ""

    def log(self, logger=None) -> None:
        """Log AND forward through the telemetry layer: the value lands in
        the metrics registry (gauge ``metrics.<name>``) and, when the event
        log is on, as a ``metric`` event — one observability pipeline for
        evaluator metrics and train-loop metrics alike."""
        from mmlspark_tpu.observability import events, metrics as obsmetrics
        from mmlspark_tpu.utils.logging import get_logger
        (logger or get_logger("metrics")).info(
            "metric %s=%.6g%s", self.name, self.value,
            f" model={self.model_uid}" if self.model_uid else "")
        obsmetrics.gauge(f"metrics.{self.name}").set(self.value)
        if events.events_enabled():
            events.emit("metric", self.name, value=self.value,
                        model=self.model_uid)


@dataclass(frozen=True)
class MetricTable:
    name: str
    columns: Sequence[str]
    rows: Any  # (n, len(columns)) array-like
    model_uid: str = ""

    def to_frame(self):
        from mmlspark_tpu.core.frame import Frame
        arr = np.asarray(self.rows)
        return Frame.from_dict(
            {c: arr[:, i] for i, c in enumerate(self.columns)})

    def log(self, logger=None) -> None:
        from mmlspark_tpu.observability import events
        from mmlspark_tpu.utils.logging import get_logger
        log = logger or get_logger("metrics")
        arr = np.asarray(self.rows)
        log.info("metric table %s (%d rows x %s)%s", self.name, len(arr),
                 list(self.columns),
                 f" model={self.model_uid}" if self.model_uid else "")
        if events.events_enabled():
            events.emit("metric", self.name, rows=int(len(arr)),
                        columns=list(self.columns), model=self.model_uid)


def create(name: str, value: float, model_uid: str = "") -> MetricValue:
    """``MetricData.create`` parity (Metrics.scala:37-41)."""
    return MetricValue(name, float(value), model_uid)


def create_table(name: str, columns: Sequence[str], rows: Any,
                 model_uid: str = "") -> MetricTable:
    """``MetricData.createTable`` parity (Metrics.scala:42-47)."""
    return MetricTable(name, list(columns), rows, model_uid)
