"""DiskFrame: a bigger-than-memory Frame backed by memory-mapped chunks.

The reference inherited out-of-core datasets from Spark (L0): a DataFrame's
partitions lived on disk/HDFS and streamed through executors. This is the
single-host TPU-native equivalent: a Frame whose partitions are directories
of per-column ``.npy`` chunks opened with ``mmap_mode='r'`` — touching a
batch faults in only that batch's pages, the OS evicts cold pages, and the
training loop's working set stays O(chunk) regardless of dataset size.

Layout on disk::

    <dir>/schema.json                 column schemas + chunk row counts
    <dir>/chunk00000/<column>.npy     one plain .npy per column per chunk

Write side: :func:`write_frame` accepts an in-memory Frame OR an iterator
of host-batch dicts (e.g. a featurize pipeline draining
``stream_binary_files``), so corpora larger than RAM can be STAGED without
ever being resident. Numeric/vector columns only — object columns (strings,
images) have no mmap representation; featurize first.

Read side: :meth:`DiskFrame.open` returns a Frame whose ``batches`` /
``_streaming_moments`` consumers work unchanged. ``shuffled_batches`` is
overridden with a bounded-memory two-level shuffle (chunk order, then rows
within a window of chunks) — epoch order is still seeded/deterministic but
is NOT the global uniform permutation an in-memory Frame draws; that is the
out-of-core tradeoff (the same one Spark made: shuffle within partition
granularity).

DeepClassifier composes with this out of the box: the DeviceEpochCache
budget check sees the true row count via shape stand-ins and declines
over-budget epochs WITHOUT materializing anything, falling back to the
streaming path, which pulls shuffled host batches through this class.
Exception: ``validationSplit`` would have to materialize the frame, so it
refuses a DiskFrame — stage separate train/val DiskFrame directories
instead.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.schema import ColumnSchema, DType, Schema, SchemaError

_SCHEMA_FILE = "schema.json"


def _cat_copy(arrs: List[np.ndarray]) -> np.ndarray:
    """Concatenate into a REAL in-memory array. Unlike Frame's `_cat`, the
    single-element case still copies — a view into a released mmap would
    silently re-fault (and re-retain) the evicted pages downstream."""
    if len(arrs) == 1:
        return np.array(arrs[0])
    return np.concatenate(arrs, axis=0)


class _LazyPartition(Mapping):
    """Dict-like partition whose column arrays are mmap-opened on access."""

    def __init__(self, directory: str, names: Sequence[str], rows: int):
        self._dir = directory
        self._names = list(names)
        self._rows = rows
        self._open: Dict[str, np.ndarray] = {}

    def __getitem__(self, name: str) -> np.ndarray:
        arr = self._open.get(name)
        if arr is None:
            if name not in self._names:
                raise KeyError(name)
            arr = np.load(os.path.join(self._dir, f"{name}.npy"),
                          mmap_mode="r")
            self._open[name] = arr
        return arr

    def __iter__(self) -> Iterator[str]:
        return iter(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def keys(self):
        return list(self._names)

    def release(self) -> None:
        """Evict this chunk's resident pages (madvise DONTNEED). The
        mapping stays valid — later access re-faults from disk — so the
        epoch's high-water resident set is the sliding window, not the
        whole file (without this, a full pass would look like the entire
        dataset is 'in memory' to RSS accounting even though the pages are
        reclaimable page cache)."""
        import mmap as _mmap
        for arr in self._open.values():
            mm = getattr(arr, "_mmap", None)
            if mm is not None:
                try:
                    mm.madvise(_mmap.MADV_DONTNEED)
                except (AttributeError, ValueError, OSError):
                    pass  # platform without madvise: pages stay cached


def _check_columns(schema: Schema) -> None:
    bad = [c.name for c in schema
           if c.dtype not in (DType.VECTOR, DType.FLOAT32, DType.FLOAT64,
                              DType.INT32, DType.INT64, DType.BOOL)]
    if bad:
        raise SchemaError(
            f"DiskFrame supports numeric/vector columns only; featurize "
            f"first (object columns: {bad})")


def write_frame(source, directory: str, rows_per_chunk: int = 65536,
                schema: Optional[Schema] = None) -> str:
    """Stage ``source`` (a Frame, or an iterator of host-batch dicts) as a
    DiskFrame directory. Streaming sources never materialize more than one
    chunk of rows; an input Frame streams through ``batches``."""
    if isinstance(source, Frame):
        schema = source.schema
        batches = source.batches(rows_per_chunk)
    else:
        if schema is None:
            raise SchemaError(
                "write_frame(iterator, ...) requires an explicit schema")
        batches = iter(source)
    _check_columns(schema)
    os.makedirs(directory, exist_ok=True)
    chunk_rows: List[int] = []
    buf: Optional[Dict[str, List[np.ndarray]]] = None
    buffered = 0

    def flush(cols: Dict[str, np.ndarray]) -> None:
        sub = os.path.join(directory, f"chunk{len(chunk_rows):05d}")
        os.makedirs(sub, exist_ok=True)
        n = None
        for name, arr in cols.items():
            arr = np.ascontiguousarray(arr)
            if arr.dtype == np.object_:
                raise SchemaError(f"column {name!r} is not mmap-able")
            np.save(os.path.join(sub, f"{name}.npy"), arr,
                    allow_pickle=False)
            n = len(arr) if n is None else n
        chunk_rows.append(int(n or 0))

    # VECTOR storage dtype is decided ONCE per column — from its first
    # batch — not per batch: a streaming source mixing uint8 and float
    # batches must not write mixed-dtype chunks (DiskFrame.open bypasses
    # Frame._unify_vector_dtypes, so mixed chunks would retrace jitted
    # consumers mid-stream). A later uint8 batch in a float column is
    # promoted; a later float batch in a uint8 column raises — silent
    # uint8 quantization of real values is never acceptable.
    vector_dtypes: Dict[str, np.dtype] = {}

    def cast(name: str, arr: np.ndarray) -> np.ndarray:
        """Pin every chunk to ONE storage dtype per column (the invariant
        Frame.__init__._unify_vector_dtypes enforces for in-memory frames;
        mixed chunks would silently retrace jitted consumers mid-stream)."""
        col = schema[name]
        if col.dtype == DType.VECTOR:
            want = vector_dtypes.setdefault(
                name, np.dtype(np.uint8 if arr.dtype == np.uint8
                               else np.float32))
            if want == np.uint8 and arr.dtype != np.uint8:
                raise SchemaError(
                    f"column {name!r} stored as uint8 (from its first "
                    f"batch) but a later batch has dtype {arr.dtype}; "
                    f"cast the source to one dtype before write_frame")
            return arr if arr.dtype == want else arr.astype(want)
        want = col.dtype.numpy_dtype
        return arr if arr.dtype == want else arr.astype(want)

    for hb in batches:
        if set(hb) != set(schema.names):
            raise SchemaError(
                f"batch columns {sorted(hb)} != schema {schema.names}")
        hb = {k: cast(k, np.asarray(v)) for k, v in hb.items()}
        lens = {k: len(v) for k, v in hb.items()}
        if len(set(lens.values())) > 1:
            raise SchemaError(f"ragged batch: column lengths {lens}")
        n = len(next(iter(hb.values())))
        if buf is None:
            buf = {k: [] for k in hb}
        for k, v in hb.items():
            buf[k].append(v)
        buffered += n
        while buffered >= rows_per_chunk:
            cat = {k: np.concatenate(v) if len(v) > 1 else v[0]
                   for k, v in buf.items()}
            flush({k: v[:rows_per_chunk] for k, v in cat.items()})
            buf = {k: [v[rows_per_chunk:]] for k, v in cat.items()}
            buffered -= rows_per_chunk
    if buffered:
        flush({k: np.concatenate(v) if len(v) > 1 else v[0]
               for k, v in buf.items()})
    meta = {"columns": [c.to_json() for c in schema],
            "chunk_rows": chunk_rows}
    with open(os.path.join(directory, _SCHEMA_FILE), "w") as f:
        json.dump(meta, f)
    return directory


class DiskFrame(Frame):
    """Frame over memory-mapped chunk partitions (see module docstring)."""

    # consumers that would otherwise materialize the whole frame (e.g.
    # DeepClassifier's validationSplit) check this and refuse
    _out_of_core = True

    @staticmethod
    def open(directory: str) -> "DiskFrame":
        with open(os.path.join(directory, _SCHEMA_FILE)) as f:
            meta = json.load(f)
        schema = Schema([ColumnSchema.from_json(d) for d in meta["columns"]])
        parts = [
            _LazyPartition(os.path.join(directory, f"chunk{i:05d}"),
                           schema.names, rows)
            for i, rows in enumerate(meta["chunk_rows"])]
        frame = DiskFrame.__new__(DiskFrame)
        # bypass Frame.__init__'s eager ragged-check (it would open every
        # chunk's memmaps up front); chunk lengths were recorded at write
        frame.schema = schema
        frame.partitions = parts
        frame._column_cache = {}
        return frame

    def count(self) -> int:
        return sum(p._rows for p in self.partitions)

    # Frame.batches drives the loop; these hooks add the out-of-core
    # behavior: batches must be REAL arrays (not views into evictable
    # mmaps), and a chunk's pages evict once it is fully consumed.
    _cat_batch = staticmethod(_cat_copy)

    def _partition_consumed(self, p) -> None:
        p.release()

    def shuffled_batches(self, batch_size: int,
                         cols: Optional[Sequence[str]] = None,
                         rng: Optional[np.random.Generator] = None,
                         drop_remainder: bool = False,
                         window_chunks: int = 4
                         ) -> Iterator[Dict[str, np.ndarray]]:
        """Bounded-memory two-level shuffle: chunk order is permuted, then
        rows are permuted WITHIN a sliding window of ``window_chunks``
        chunks — memory stays O(window), order is seeded-deterministic.
        Not the global uniform permutation of an in-memory Frame (the
        Spark-era tradeoff, made explicit)."""
        rng = rng if rng is not None else np.random.default_rng()
        cols = list(cols) if cols is not None else self.schema.names
        order = rng.permutation(len(self.partitions))
        buf: Dict[str, List[np.ndarray]] = {c: [] for c in cols}
        pending: List[_LazyPartition] = []
        held = 0

        def drain(final: bool):
            nonlocal buf, held
            cat = {c: _cat_copy(buf[c]) for c in cols}
            for p in pending:  # window copied out: evict the chunk pages
                p.release()
            pending.clear()
            n = len(cat[cols[0]])
            perm = rng.permutation(n)
            end = n if final else n - n % batch_size
            for off in range(0, end, batch_size):
                idx = perm[off:off + batch_size]
                if len(idx) < batch_size and (drop_remainder or not final):
                    break
                yield {c: cat[c][idx] for c in cols}
            tail = perm[end:]
            buf = {c: [cat[c][tail]] for c in cols}
            held = len(tail)

        for pi in order:
            p = self.partitions[pi]
            for c in cols:
                buf[c].append(p[c])
            pending.append(p)
            held += p._rows
            if len(pending) >= window_chunks:
                yield from drain(final=False)
        if held:
            yield from drain(final=True)
