"""Param DSL: typed, validated, JSON-serializable stage parameters.

Re-expression of the reference's MML param system
(``core/contracts/src/main/scala/Params.scala:12-134``): factory methods
producing params with defaults and string domains, plus shared-column mixins
(``HasInputCol``/``HasOutputCol``/``HasLabelCol``/``HasFeaturesCol``).

Differences from the reference, by design:
- No JVM reflection; params are plain descriptors on Python classes.
- JSON is the single serialization dialect (the reference splits between
  Spark ML param JSON and java serialization).
"""
from __future__ import annotations

import copy
import uuid
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

_UNSET = object()


class ParamException(ValueError):
    """Raised when a param value fails validation.

    Reference: ``core/contracts/src/main/scala/Exceptions.scala:27-36``.
    """


class Param:
    """A single named, documented, optionally-validated parameter.

    Mirrors the reference's ``Wrappable.BooleanParam/IntParam/...`` factories
    (``Params.scala:12-110``) as one descriptor with a ``dtype`` and optional
    ``domain`` (string-domain validation) or ``validator`` predicate.
    """

    def __init__(
        self,
        name: str,
        doc: str,
        default: Any = _UNSET,
        dtype: Optional[type] = None,
        domain: Optional[Sequence[Any]] = None,
        validator: Optional[Callable[[Any], bool]] = None,
    ):
        self.name = name
        self.doc = doc
        self.default = default
        self.dtype = dtype
        self.domain = tuple(domain) if domain is not None else None
        self.validator = validator

    @property
    def has_default(self) -> bool:
        return self.default is not _UNSET

    def validate(self, value: Any) -> Any:
        if self.dtype is not None and value is not None:
            # numpy scalars arrive constantly in a numpy-centric framework
            if isinstance(value, np.bool_):
                value = bool(value)
            elif isinstance(value, np.integer):
                value = int(value)
            elif isinstance(value, np.floating):
                value = float(value)
            if self.dtype is float and isinstance(value, int) and not isinstance(value, bool):
                value = float(value)
            if not isinstance(value, self.dtype):
                raise ParamException(
                    f"param {self.name!r}: expected {self.dtype.__name__}, "
                    f"got {type(value).__name__} ({value!r})"
                )
        if self.domain is not None and value not in self.domain:
            raise ParamException(
                f"param {self.name!r}: {value!r} not in domain {list(self.domain)}"
            )
        if self.validator is not None and not self.validator(value):
            raise ParamException(f"param {self.name!r}: {value!r} failed validation")
        return value

    def __repr__(self) -> str:  # pragma: no cover
        return f"Param({self.name!r})"

    # Descriptor protocol: `stage.paramName` reads the current value.
    def __set_name__(self, owner, attr_name):
        self._attr = attr_name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        return obj.get(self)

    def __set__(self, obj, value):
        obj.set(self, value)


def BooleanParam(name: str, doc: str, default: Any = _UNSET) -> Param:
    return Param(name, doc, default, dtype=bool)


def IntParam(name: str, doc: str, default: Any = _UNSET, validator=None) -> Param:
    return Param(name, doc, default, dtype=int, validator=validator)


def FloatParam(name: str, doc: str, default: Any = _UNSET, validator=None) -> Param:
    return Param(name, doc, default, dtype=float, validator=validator)


def StringParam(
    name: str, doc: str, default: Any = _UNSET, domain: Optional[Sequence[str]] = None
) -> Param:
    return Param(name, doc, default, dtype=str, domain=domain)


def ListParam(name: str, doc: str, default: Any = _UNSET) -> Param:
    return Param(name, doc, default, dtype=list)


def DictParam(name: str, doc: str, default: Any = _UNSET) -> Param:
    return Param(name, doc, default, dtype=dict)


def AnyParam(name: str, doc: str, default: Any = _UNSET) -> Param:
    """Param holding arbitrary objects (estimators, transformers, arrays).

    Counterpart of the reference's ``EstimatorParam``/``TransformerParam``/
    ``TransformerArrayParam`` (``core/spark/src/main/scala/TransformParam.scala``).
    Serialized via the stage-serialization layer, not plain JSON.
    """
    return Param(name, doc, default)


class Params:
    """Base for anything carrying params; tracks explicitly-set vs default values.

    The `uid` follows the reference convention (`ClassName_xxxxxxxx`).
    """

    def __init__(self, uid: Optional[str] = None, **kwargs):
        self.uid = uid or f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._paramMap: Dict[str, Any] = {}
        self.set_params(**kwargs)

    # -- param discovery ---------------------------------------------------
    @classmethod
    def params(cls) -> List[Param]:
        out, seen = [], set()
        for klass in cls.__mro__:
            for v in vars(klass).values():
                if isinstance(v, Param) and v.name not in seen:
                    seen.add(v.name)
                    out.append(v)
        return out

    @classmethod
    def get_param(cls, name: str) -> Param:
        for p in cls.params():
            if p.name == name:
                return p
        raise ParamException(f"{cls.__name__} has no param {name!r}")

    # -- get/set -----------------------------------------------------------
    @staticmethod
    def _unchanged(cur, new) -> bool:
        if cur is new:
            return True
        try:
            return bool(cur == new)
        except Exception:  # ambiguous comparisons (arrays) -> treat as changed
            return False

    def set(self, param, value) -> "Params":
        if isinstance(param, str):
            param = self.get_param(param)
        value = param.validate(value)
        # compiled closures may capture param values — but a no-op set must
        # not throw away a 20-40s TPU compile (e.g. re-stamping the same
        # inputCol on a cached scoring model every transform() call)
        if not (param.name in self._paramMap
                and self._unchanged(self._paramMap[param.name], value)):
            self._jit_cache = None
        self._paramMap[param.name] = value
        return self

    def set_params(self, **kwargs) -> "Params":
        for k, v in kwargs.items():
            self.set(k, v)
        return self

    def get(self, param, default: Any = _UNSET) -> Any:
        if isinstance(param, str):
            param = self.get_param(param)
        if param.name in self._paramMap:
            return self._paramMap[param.name]
        if param.has_default:
            return copy.copy(param.default)
        if default is not _UNSET:
            return default
        raise ParamException(
            f"{type(self).__name__}: param {param.name!r} is not set and has no default"
        )

    def is_set(self, param) -> bool:
        if isinstance(param, str):
            param = self.get_param(param)
        return param.name in self._paramMap

    def is_defined(self, param) -> bool:
        if isinstance(param, str):
            param = self.get_param(param)
        return param.name in self._paramMap or param.has_default

    def copy(self) -> "Params":
        other = copy.copy(self)
        other._paramMap = dict(self._paramMap)
        if hasattr(self, "_state"):
            other._state = copy.deepcopy(self._state)
        other._jit_cache = None  # never share compiled closures with the copy
        return other

    def explain_params(self) -> str:
        lines = []
        for p in sorted(self.params(), key=lambda p: p.name):
            cur = self._paramMap.get(p.name, p.default if p.has_default else "<unset>")
            lines.append(f"{p.name}: {p.doc} (current: {cur!r})")
        return "\n".join(lines)

    def explicit_param_values(self) -> Dict[str, Any]:
        return dict(self._paramMap)

    def __repr__(self) -> str:
        kv = ", ".join(f"{k}={v!r}" for k, v in sorted(self._paramMap.items()))
        return f"{type(self).__name__}(uid={self.uid!r}, {kv})"


# -- shared-column mixins (reference Params.scala:112-134) -------------------
class HasInputCol(Params):
    inputCol = StringParam("inputCol", "name of the input column", "input")


class HasOutputCol(Params):
    outputCol = StringParam("outputCol", "name of the output column", "output")


class HasInputCols(Params):
    inputCols = ListParam("inputCols", "names of the input columns")


class HasLabelCol(Params):
    labelCol = StringParam("labelCol", "name of the label column", "label")


class HasFeaturesCol(Params):
    featuresCol = StringParam("featuresCol", "name of the features column", "features")
