"""Estimator / Transformer / Model / Pipeline contracts.

Same user-facing contract as the reference (it is what the reference's whole
L4/L5 stack — and its users — are written against), re-hosted on Frame:
``fit``/``transform`` bodies JIT to XLA where they touch tensors.

Reference: Spark ML's PipelineStage hierarchy as used throughout
``/root/reference/src`` (e.g. ``TrainClassifier.scala:81``,
``Featurize.scala:67``); save/load via the serialization layer replaces
``PipelineUtilities.saveMetadata`` (``utils/src/main/scala/PipelineUtilities.scala:19-47``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from mmlspark_tpu.core.frame import Frame
from mmlspark_tpu.core.params import AnyParam, Params
from mmlspark_tpu.core.schema import Schema
from mmlspark_tpu.observability.spans import span


class PipelineStage(Params):
    """Anything that can sit in a Pipeline and be saved/loaded."""

    def save(self, path: str) -> None:
        from mmlspark_tpu.core.serialization import save_stage
        save_stage(self, path)

    @classmethod
    def load(cls, path: str) -> "PipelineStage":
        from mmlspark_tpu.core.serialization import load_stage
        stage = load_stage(path)
        if cls is not PipelineStage and not isinstance(stage, cls):
            raise TypeError(f"loaded {type(stage).__name__}, expected {cls.__name__}")
        return stage

    # Learned state hook: dict pytree with ndarray leaves; see serialization.py.
    def _get_state(self) -> Dict[str, Any]:
        return getattr(self, "_state", {}) or {}

    def _set_state(self, state: Dict[str, Any]) -> None:
        self._jit_cache = None  # compiled closures are stale once state changes
        # caches derived FROM a compiled closure (e.g. JaxModel's
        # eval_shape memo keys on the closure object) must die with it, or
        # they pin the old closure — and the whole param tree it captured
        self._out_spec_cache = None
        if state:
            self._state = state

    def _cached_jit(self, builder, key: Any = None):
        """Memoize a jitted closure over this stage's state: the first jit
        compile on TPU is 20-40s, so repeat transform() calls must not pay it
        again. Invalidated by _set_state and copy(), and by a changed
        ``key`` — pass the params the closure bakes in (output node,
        preprocessing spec, ...) so editing them between transforms can't
        serve a stale program."""
        cached = getattr(self, "_jit_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        fn = builder()
        self._jit_cache = (key, fn)
        return fn


class Transformer(PipelineStage):
    def transform(self, frame: Frame) -> Frame:
        raise NotImplementedError

    def transform_schema(self, schema: Schema) -> Schema:
        """Best-effort schema-out-of-schema (used by validation & codegen)."""
        return schema

    def __call__(self, frame: Frame) -> Frame:
        return self.transform(frame)


class Estimator(PipelineStage):
    def fit(self, frame: Frame) -> "Transformer":
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer (kept as a distinct type for API parity)."""


class Pipeline(Estimator):
    """Sequential composition of stages; estimators are fitted in order and
    replaced by the models they produce, exactly like Spark's Pipeline."""

    stages = AnyParam("stages", "ordered list of pipeline stages", default=[])

    def fit(self, frame: Frame) -> "PipelineModel":
        stages = self.get("stages")
        for i, stage in enumerate(stages):
            if not isinstance(stage, (Estimator, Transformer)):
                raise TypeError(f"stage {i} ({type(stage).__name__}) is neither "
                                "Estimator nor Transformer")
        # No frame pass is needed beyond the last estimator (Spark semantics).
        last_est = max((i for i, s in enumerate(stages) if isinstance(s, Estimator)),
                       default=-1)
        fitted: List[Transformer] = []
        cur = frame
        # per-stage telemetry spans (no-ops unless observability.* is on);
        # the outer span parents them so the event log nests fit:Pipeline ->
        # fit:<Stage> -> transform:<Stage>
        with span("fit", type(self).__name__):
            for i, stage in enumerate(stages):
                if isinstance(stage, Estimator):
                    with span("fit", type(stage).__name__, stage=i):
                        model = stage.fit(cur)
                else:
                    model = stage
                if i < last_est:
                    with span("transform", type(model).__name__, stage=i):
                        cur = model.transform(cur)
                fitted.append(model)
        return PipelineModel(stages=fitted)


class PipelineModel(Model):
    stages = AnyParam("stages", "ordered list of fitted transformers", default=[])

    def transform(self, frame: Frame) -> Frame:
        with span("transform", type(self).__name__):
            for stage in self.get("stages"):
                with span("transform", type(stage).__name__):
                    frame = stage.transform(frame)
        return frame

    def transform_schema(self, schema: Schema) -> Schema:
        for stage in self.get("stages"):
            schema = stage.transform_schema(schema)
        return schema
