"""Column schema + metadata: the glue that lets evaluators discover columns.

Re-expression of the reference's schema layer:
- ``SchemaConstants`` (``core/schema/src/main/scala/SchemaConstants.scala:9-45``)
- ``SparkSchema`` score-column tagging/discovery (``SparkSchema.scala:26-245``)
- ``Categoricals`` level<->index maps with null handling
  (``Categoricals.scala:187-356``)
- ``ImageSchema``/``BinaryFileSchema`` column types
  (``ImageSchema.scala:18-23``, ``BinaryFileSchema.scala:14-17``)

TPU-first design: metadata rides on the Frame's per-column ``ColumnSchema``
as plain JSON-able dicts, so it survives save/load and streams with the data
into sharded device arrays without a JVM metadata dialect.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class DType(str, enum.Enum):
    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    STRING = "string"
    BINARY = "binary"      # bytes per row (reference BinaryFileSchema)
    VECTOR = "vector"      # fixed-dim vector per row (2D ndarray storage;
                           # float32 canonical, uint8 permitted as the
                           # raw-bytes wire format — cast before arithmetic)
    IMAGE = "image"        # decoded image struct per row (reference ImageSchema)
    TOKENS = "tokens"      # list[str] per row (tokenizer output)

    @property
    def is_numeric(self) -> bool:
        return self in (DType.BOOL, DType.INT32, DType.INT64, DType.FLOAT32, DType.FLOAT64)

    @property
    def numpy_dtype(self):
        return {
            DType.BOOL: np.bool_, DType.INT32: np.int32, DType.INT64: np.int64,
            DType.FLOAT32: np.float32, DType.FLOAT64: np.float64,
        }.get(self, np.object_)


# -- score-column metadata tags (reference SchemaConstants.scala:9-45) -------
class ScoreKind:
    MML = "mml"                     # metadata namespace key
    SCORES = "scores"
    SCORED_LABELS = "scored_labels"
    SCORED_PROBABILITIES = "scored_probabilities"
    TRUE_LABELS = "true_labels"
    RAW_PREDICTION = "raw_prediction"

    CLASSIFICATION = "classification"
    REGRESSION = "regression"


class SchemaError(ValueError):
    pass


@dataclass
class CategoricalMap:
    """level <-> index map with optional null level.

    Reference ``CategoricalMap[T]`` (``Categoricals.scala:187-262``): stores
    ordered levels, optionally treats one index as the null/missing level,
    serializes into column metadata.
    """
    levels: List[Any]
    has_null_level: bool = False

    def __post_init__(self):
        self._index: Dict[Any, int] = {v: i for i, v in enumerate(self.levels)}

    @property
    def num_levels(self) -> int:
        return len(self.levels)

    def get_index(self, level: Any, default: Optional[int] = None) -> int:
        idx = self._index.get(level, -1)
        if idx >= 0:
            return idx
        if default is not None:
            return default
        raise SchemaError(f"level {level!r} not found in categorical map")

    def get_level(self, index: int) -> Any:
        if 0 <= index < len(self.levels):
            return self.levels[index]
        raise SchemaError(f"index {index} out of range [0, {len(self.levels)})")

    def to_metadata(self) -> Dict[str, Any]:
        return {"levels": list(self.levels), "has_null_level": self.has_null_level}

    @staticmethod
    def from_metadata(md: Dict[str, Any]) -> "CategoricalMap":
        return CategoricalMap(list(md["levels"]), bool(md.get("has_null_level", False)))


@dataclass
class ColumnSchema:
    """Name, type, per-column metadata; VECTOR columns carry their dim.

    ``metadata`` keys in use:
      - ``categorical``: CategoricalMap.to_metadata() payload
      - ``score_kind``: one of ScoreKind.{SCORES,...}
      - ``score_value_kind``: ScoreKind.{CLASSIFICATION,REGRESSION}
      - ``model_uid``: uid of the model that produced the column
    """
    name: str
    dtype: DType
    dim: Optional[int] = None          # for VECTOR columns
    metadata: Dict[str, Any] = field(default_factory=dict)

    def with_meta(self, **kv) -> "ColumnSchema":
        md = dict(self.metadata)
        md.update(kv)
        return ColumnSchema(self.name, self.dtype, self.dim, md)

    def renamed(self, name: str) -> "ColumnSchema":
        return ColumnSchema(name, self.dtype, self.dim, dict(self.metadata))

    @property
    def categorical(self) -> Optional[CategoricalMap]:
        md = self.metadata.get("categorical")
        return CategoricalMap.from_metadata(md) if md else None

    @property
    def is_categorical(self) -> bool:
        return "categorical" in self.metadata

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "dtype": self.dtype.value, "dim": self.dim,
                "metadata": self.metadata}

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "ColumnSchema":
        return ColumnSchema(d["name"], DType(d["dtype"]), d.get("dim"),
                            dict(d.get("metadata", {})))


@dataclass
class Schema:
    columns: List[ColumnSchema]

    def __post_init__(self):
        names = [c.name for c in self.columns]
        if len(set(names)) != len(names):
            raise SchemaError(f"duplicate column names in schema: {names}")

    @property
    def names(self) -> List[str]:
        return [c.name for c in self.columns]

    def __contains__(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    def __getitem__(self, name: str) -> ColumnSchema:
        for c in self.columns:
            if c.name == name:
                return c
        raise SchemaError(f"column {name!r} not in schema (have {self.names})")

    def __iter__(self):
        return iter(self.columns)

    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self[n] for n in names])

    def drop(self, names: Sequence[str]) -> "Schema":
        names = set(names)
        return Schema([c for c in self.columns if c.name not in names])

    def add(self, col: ColumnSchema) -> "Schema":
        if col.name in self:
            return Schema([col if c.name == col.name else c for c in self.columns])
        return Schema(self.columns + [col])

    def find_unused_name(self, prefix: str) -> str:
        """Collision-free temp column name (reference DatasetExtensions.scala:23-40)."""
        if prefix not in self:
            return prefix
        i = 1
        while f"{prefix}_{i}" in self:
            i += 1
        return f"{prefix}_{i}"

    def to_json(self) -> List[Dict[str, Any]]:
        return [c.to_json() for c in self.columns]

    @staticmethod
    def from_json(lst: List[Dict[str, Any]]) -> "Schema":
        return Schema([ColumnSchema.from_json(d) for d in lst])


# -- score-column tagging/discovery (reference SparkSchema.scala) ------------
def set_score_column(schema: Schema, col: str, model_uid: str, score_kind: str,
                     score_value_kind: str) -> Schema:
    """Stamp score metadata on a column so evaluators can discover it.

    Reference: ``SparkSchema.scala`` setters at ``:26-63`` / ``updateMetadata``
    at ``:209-236``.
    """
    tagged = schema[col].with_meta(
        score_kind=score_kind, score_value_kind=score_value_kind, model_uid=model_uid)
    return schema.add(tagged)


def find_score_column(schema: Schema, score_kind: str,
                      model_uid: Optional[str] = None) -> Optional[str]:
    """Find the column tagged with a given score kind (SparkSchema getters :72-143)."""
    for c in schema:
        if c.metadata.get("score_kind") == score_kind:
            if model_uid is None or c.metadata.get("model_uid") == model_uid:
                return c.name
    return None


def find_score_value_kind(schema: Schema) -> Optional[str]:
    """Classification vs regression, discovered from any scored column."""
    for c in schema:
        if "score_value_kind" in c.metadata:
            return c.metadata["score_value_kind"]
    return None


# -- image schema (reference ImageSchema.scala:18-23) ------------------------
@dataclass
class ImageValue:
    """One decoded image: uint8 HWC array in BGR channel order + provenance.

    The reference stores ``(path, height, width, type, bytes)`` with row-wise
    BGR bytes (OpenCV CV_8U). We keep the same logical fields but store the
    pixels as a numpy array so TPU featurization can stack batches without
    re-parsing bytes.
    """
    path: Optional[str]
    data: np.ndarray  # uint8, shape (H, W, C), BGR

    @property
    def height(self) -> int:
        return int(self.data.shape[0])

    @property
    def width(self) -> int:
        return int(self.data.shape[1])

    @property
    def channels(self) -> int:
        return int(self.data.shape[2]) if self.data.ndim == 3 else 1
