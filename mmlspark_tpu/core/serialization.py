"""Stage save/load: metadata JSON + numpy blob archives + nested stages.

Replaces the reference's three serialization mechanisms with one:
- Spark ML param JSON (``PipelineUtilities.saveMetadata``,
  ``utils/src/main/scala/PipelineUtilities.scala:19-47``)
- parquet data parts
- Java-serialized objects (``ObjectUtilities.scala:13-71``)

Layout of a saved stage directory:
    metadata.json   {class, uid, version, params: {...}, state: <encoded pytree>}
    arrays.npz      ndarray leaves referenced from metadata.json by key
    params/<name>/  nested stage(s) for params holding stages

A class registry (populated by the ``@register_stage`` decorator) maps the
qualified class name back to the class at load time; it doubles as the stage
inventory that codegen and the fuzzing harness introspect (the TPU-native
equivalent of ``JarLoadingUtils`` reflection, ``utils/src/main/scala/JarLoadingUtils.scala:18-139``).
"""
from __future__ import annotations

import importlib
import json
import os
from typing import Any, Dict, List, Optional, Tuple, Type

import numpy as np

FORMAT_VERSION = 1

_STAGE_REGISTRY: Dict[str, Type] = {}


def register_stage(cls=None):
    """Class decorator adding the stage to the global registry."""
    def wrap(c):
        _STAGE_REGISTRY[f"{c.__module__}.{c.__name__}"] = c
        _STAGE_REGISTRY[c.__name__] = c
        return c
    return wrap(cls) if cls is not None else wrap


def registered_stages() -> Dict[str, Type]:
    """Qualified-name -> class map (short-name aliases filtered out)."""
    return {k: v for k, v in _STAGE_REGISTRY.items() if "." in k}


def _resolve_class(qualname: str) -> Type:
    if qualname in _STAGE_REGISTRY:
        return _STAGE_REGISTRY[qualname]
    module, _, name = qualname.rpartition(".")
    cls = getattr(importlib.import_module(module), name)
    return cls


# -- pytree <-> (json, arrays) codec ----------------------------------------
def _encode(obj: Any, arrays: Dict[str, np.ndarray], path: str) -> Any:
    if isinstance(obj, np.ndarray):
        if obj.dtype == np.object_:
            raise TypeError(
                f"object ndarray at state path {path!r} cannot be serialized "
                "safely; convert to a list or a typed array first")
        arrays[path] = obj
        return {"__nd__": path}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, bytes):
        arrays[path] = np.frombuffer(obj, dtype=np.uint8)
        return {"__bytes__": path}
    if isinstance(obj, dict):
        if all(isinstance(k, str) for k in obj):
            return {"__dict__": {k: _encode(v, arrays, f"{path}/{k}")
                                 for k, v in obj.items()}}
        # non-string keys (e.g. index->label maps): store as key/value pairs
        return {"__items__": [
            [_encode(k, arrays, f"{path}/k{i}"), _encode(v, arrays, f"{path}/v{i}")]
            for i, (k, v) in enumerate(obj.items())]}
    if isinstance(obj, tuple):
        return {"__tuple__": [_encode(v, arrays, f"{path}/{i}")
                              for i, v in enumerate(obj)]}
    if isinstance(obj, list):
        return [_encode(v, arrays, f"{path}/{i}") for i, v in enumerate(obj)]
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    mesh_dict = _mesh_to_dict(obj)
    if mesh_dict is not None:
        return _encode(mesh_dict, arrays, path)
    raise TypeError(f"cannot serialize {type(obj).__name__} at state path {path!r}")


def _mesh_to_dict(obj: Any):
    """Mesh-shaped param values (DeepClassifier/JaxModel meshSpec) persist
    as axis-size dicts: a live Mesh is process-bound (its device list has
    no meaning in another process) and ``resolve_mesh`` accepts the dict
    back, so save/load round-trips the SHAPE — the portable part.
    Returns None for non-mesh objects."""
    try:
        from dataclasses import asdict
        from jax.sharding import Mesh
        from mmlspark_tpu.parallel.mesh import MeshSpec
    except ImportError:  # pragma: no cover - jax always present here
        return None
    if isinstance(obj, MeshSpec):
        return asdict(obj)
    if isinstance(obj, Mesh):
        from mmlspark_tpu.parallel.mesh import AXES
        bad = sorted(set(obj.shape) - set(AXES))
        if bad:
            raise TypeError(
                f"cannot persist a Mesh with non-standard axes {bad}: "
                f"resolve_mesh could not rebuild it at load; use the "
                f"standard axis names {AXES}")
        return {k: int(v) for k, v in obj.shape.items()}
    return None


def _decode(obj: Any, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(obj, dict):
        if "__nd__" in obj:
            return arrays[obj["__nd__"]]
        if "__bytes__" in obj:
            return arrays[obj["__bytes__"]].tobytes()
        if "__dict__" in obj:
            return {k: _decode(v, arrays) for k, v in obj["__dict__"].items()}
        if "__items__" in obj:
            return {_decode(k, arrays): _decode(v, arrays)
                    for k, v in obj["__items__"]}
        if "__tuple__" in obj:
            return tuple(_decode(v, arrays) for v in obj["__tuple__"])
    if isinstance(obj, list):
        return [_decode(v, arrays) for v in obj]
    return obj


# -- param value encoding (may contain nested stages) ------------------------
def _is_stage(v: Any) -> bool:
    from mmlspark_tpu.core.pipeline import PipelineStage
    return isinstance(v, PipelineStage)


def _encode_param(name: str, value: Any, path: str,
                  arrays: Dict[str, np.ndarray]) -> Any:
    if _is_stage(value):
        sub = os.path.join(path, "params", name)
        save_stage(value, sub)
        return {"__stage__": f"params/{name}"}
    if isinstance(value, list) and any(_is_stage(v) for v in value):
        rels = []
        for i, v in enumerate(value):
            sub = os.path.join(path, "params", f"{name}_{i}")
            save_stage(v, sub)
            rels.append(f"params/{name}_{i}")
        return {"__stages__": rels}
    return _encode(value, arrays, f"__param__/{name}")


def _decode_param(value: Any, path: str, arrays: Dict[str, np.ndarray]) -> Any:
    if isinstance(value, dict) and "__stage__" in value:
        return load_stage(os.path.join(path, value["__stage__"]))
    if isinstance(value, dict) and "__stages__" in value:
        return [load_stage(os.path.join(path, rel)) for rel in value["__stages__"]]
    return _decode(value, arrays)


# -- public API --------------------------------------------------------------
def save_stage(stage, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    params = {name: _encode_param(name, value, path, arrays)
              for name, value in stage.explicit_param_values().items()}
    state = _encode(stage._get_state(), arrays, "__state__")
    meta = {
        "class": f"{type(stage).__module__}.{type(stage).__name__}",
        "uid": stage.uid,
        "version": FORMAT_VERSION,
        "params": params,
        "state": state,
    }
    if arrays:
        np.savez(os.path.join(path, "arrays.npz"),
                 **{k.replace("/", "╱"): v for k, v in arrays.items()})
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=1, default=_json_fallback)


def load_stage(path: str):
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    arrays: Dict[str, np.ndarray] = {}
    npz_path = os.path.join(path, "arrays.npz")
    if os.path.exists(npz_path):
        with np.load(npz_path, allow_pickle=False) as z:
            arrays = {k.replace("╱", "/"): z[k] for k in z.files}
    cls = _resolve_class(meta["class"])
    stage = cls.__new__(cls)
    from mmlspark_tpu.core.params import Params
    Params.__init__(stage, uid=meta["uid"])
    for name, enc in meta["params"].items():
        stage.set(name, _decode_param(enc, path, arrays))
    stage._set_state(_decode(meta["state"], arrays))
    if hasattr(stage, "_post_load"):
        stage._post_load()
    return stage


def stage_fingerprint(stage) -> str:
    """Content hash of a stage: class + explicit params + state, nested
    stages included, uids EXCLUDED — two stages fit identically (same
    config, same data) fingerprint the same even though their uids differ.
    FindBestModel uses this to share one featurize pass across candidates
    whose featurization is semantically identical."""
    import hashlib
    h = hashlib.sha256()

    def feed(o):
        if _is_stage(o):
            h.update(b"\x01")
            h.update(f"{type(o).__module__}.{type(o).__name__}".encode())
            for k, v in sorted(o.explicit_param_values().items()):
                h.update(k.encode())
                feed(v)
            h.update(b"\x02")
            feed(o._get_state())
        elif isinstance(o, dict):
            h.update(b"\x03")
            for k in sorted(o, key=str):
                if str(k) in ("uid", "model_uid"):
                    continue  # identity, not content
                h.update(str(k).encode())
                feed(o[k])
        elif isinstance(o, (list, tuple)):
            h.update(b"\x04")
            for v in o:
                feed(v)
        elif isinstance(o, np.ndarray):
            h.update(b"\x05")
            h.update(str(o.dtype).encode())
            h.update(str(o.shape).encode())
            h.update(o.tobytes() if o.dtype != np.object_
                     else repr(o.tolist()).encode())
        else:
            h.update(b"\x06")
            h.update(repr(o).encode())

    feed(stage)
    return h.hexdigest()


def _json_fallback(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    mesh_dict = _mesh_to_dict(o)
    if mesh_dict is not None:
        return mesh_dict
    raise TypeError(f"not JSON serializable: {type(o).__name__}")
