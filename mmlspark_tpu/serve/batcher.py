"""Deadline-aware dynamic micro-batching core (pure logic, no threads).

The coalescing policy of the serving subsystem, factored out of the
:class:`~mmlspark_tpu.serve.server.Server` executor thread so tests drive it
with an injected clock and zero sleeps: admitted requests (:class:`Ticket`)
queue in arrival order, and a batch flushes when EITHER

- the head group reaches ``max_batch`` rows (occupancy-driven flush), or
- the oldest pending ticket has waited ``max_wait_s`` (deadline-driven
  flush — a lone request is never stranded behind an empty batch).

Batches are single-model: a group is the maximal run of consecutive
same-model tickets from the head, so multi-model traffic interleaves in
FIFO order without ever mixing two models' rows in one device program.

Bucketing: flushed groups pad to the smallest configured bucket that fits
(:func:`bucket_for`), so the jitted apply sees a SMALL FIXED SET of batch
shapes and compiles once per bucket — never per request, never per
occupancy. This is the serving-side face of the one-compiled-shape
discipline ``JaxModel.transform`` applies to final-batch padding.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple


class Ticket:
    """One admitted request: ``rows`` coerced examples bound for ``model``,
    plus the future its caller is blocked on. ``enqueued`` and ``deadline``
    are absolute times on the server's (injectable) clock; ``deadline``
    None means the request never expires. ``trace_id`` is minted at
    admission and rides through shed/expired/request events (and the
    tail-sampled span timeline) so one request's records correlate."""

    __slots__ = ("model", "x", "rows", "future", "enqueued", "deadline",
                 "trace_id")

    def __init__(self, model: str, x, rows: int, future,
                 enqueued: float, deadline: Optional[float] = None,
                 trace_id: str = ""):
        self.model = model
        self.x = x
        self.rows = rows
        self.future = future
        self.enqueued = enqueued
        self.deadline = deadline
        self.trace_id = trace_id

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline


def default_buckets(max_batch: int) -> Tuple[int, ...]:
    """The default bucket ladder: {1, max/8, max/2, max} (deduped) — four
    compiles covering lone requests, trickle traffic, and full batches.
    A geometric ladder wastes at most ~2x padding compute in the worst
    case while keeping compile count (and HBM program cache) small."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    return tuple(sorted({1, max(1, max_batch // 8),
                         max(1, max_batch // 2), max_batch}))


def parse_buckets(text: str, max_batch: int) -> Tuple[int, ...]:
    """``serving.buckets`` config ("1,8,64") -> validated ascending tuple.
    The largest bucket must cover ``max_batch`` or a full flush could not
    be padded to any compiled shape."""
    vals = sorted({int(v) for v in text.split(",") if v.strip()})
    if not vals:
        return default_buckets(max_batch)
    if any(v < 1 for v in vals):
        raise ValueError(f"buckets must be >= 1, got {vals}")
    if vals[-1] < max_batch:
        raise ValueError(
            f"largest bucket {vals[-1]} < max_batch {max_batch}; a full "
            "batch would have no compiled shape to pad to")
    return tuple(vals)


def bucket_for(rows: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``rows`` (buckets ascending)."""
    for b in buckets:
        if rows <= b:
            return b
    raise ValueError(f"{rows} rows exceed the largest bucket {buckets[-1]}")


class MicroBatcher:
    """FIFO coalescer with the two-trigger flush policy above.

    Not thread-safe by itself — the server's single executor thread is the
    only caller, which is also what makes hit order (and therefore fault
    replay) deterministic.
    """

    def __init__(self, max_batch: int, max_wait_s: float,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.clock = clock
        self._pending: "deque[Ticket]" = deque()

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def pending_rows(self) -> int:
        return sum(t.rows for t in self._pending)

    def offer(self, ticket: Ticket) -> None:
        if ticket.rows > self.max_batch:
            # submit_many splits oversized requests before admission; a
            # ticket this size is a caller bug, surfaced loudly
            raise ValueError(
                f"ticket of {ticket.rows} rows exceeds max_batch "
                f"{self.max_batch}")
        self._pending.append(ticket)

    def _head_group_rows(self) -> int:
        """Rows in the maximal consecutive same-model run from the head,
        capped at max_batch (the flushable group)."""
        rows = 0
        model = None
        for t in self._pending:
            if model is None:
                model = t.model
            elif t.model != model:
                break
            if rows + t.rows > self.max_batch:
                break
            rows += t.rows
        return rows

    def ready(self, now: Optional[float] = None) -> bool:
        """Should the head group flush now?"""
        if not self._pending:
            return False
        if now is None:
            now = self.clock()
        if self._head_group_rows() >= self.max_batch:
            return True
        return now - self._pending[0].enqueued >= self.max_wait_s

    def wait_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds until the oldest ticket forces a deadline flush, or
        None when nothing is pending (block indefinitely)."""
        if not self._pending:
            return None
        if now is None:
            now = self.clock()
        return max(0.0, self.max_wait_s - (now - self._pending[0].enqueued))

    def take(self, now: Optional[float] = None) -> List[Ticket]:
        """Pop the head group (same model, <= max_batch rows, arrival
        order). Empty list when nothing is pending. Expiry is NOT filtered
        here — the server cancels expired tickets at dequeue so the
        cancellation is observable (counted, evented) in one place."""
        group: List[Ticket] = []
        rows = 0
        while self._pending:
            head = self._pending[0]
            if group and head.model != group[0].model:
                break
            if rows + head.rows > self.max_batch:
                break
            group.append(self._pending.popleft())
            rows += head.rows
        return group
