"""Replica supervisor: real OS processes, warm restart-on-crash.

Everything below the :class:`~mmlspark_tpu.serve.router.Router` so far
lived in ONE interpreter — ``InProcessReplica.kill()`` simulates a death
without a process ever dying. This module crosses the real boundary: each
replica is a ``mmlspark-tpu serve`` *process* (its own port, its own
per-pid event-log sidecar, the SHARED persistent compile cache), and the
:class:`Supervisor` owns its lifecycle:

- **spawn**: :class:`ProcessSpawner` launches ``python -m mmlspark_tpu.cli
  serve --port 0`` and reads the one-line JSON announce from the child's
  stdout to learn the ephemeral port. The child inherits
  ``runtime.compile_cache_dir`` through its environment
  (:func:`mmlspark_tpu.compile_cache.worker_env`), so replica N+1
  cold-starts by LOADING compiled programs, not compiling them.
- **supervise**: one :meth:`Supervisor.poll_once` step reaps exits,
  schedules restarts through the existing :class:`RetryPolicy`
  exponential backoff (deterministic, non-blocking — a crash-looping
  replica never stalls supervision of the others), and feeds a
  per-replica :class:`CircuitBreaker`: a child that dies before
  ``fleet.supervisor_min_uptime_s`` counts a failure, enough consecutive
  failures trip the breaker OPEN and the replica leaves the Router
  rotation (weight 0) instead of flapping. After the cooldown the
  breaker's single half-open slot admits exactly ONE probe respawn;
  a probe crash re-opens with a fresh cooldown (the hysteresis).
- **re-register**: a restarted child gets a fresh port; the supervisor
  mutates the replica's :class:`~mmlspark_tpu.serve.router.HttpReplica`
  ``addr`` in place — object identity, router handle, and breaker history
  survive the restart, so failover, fairness, SLO burn, and the
  aggregated dashboard keep working across it.
- **drain**: SIGTERM to the supervisor (via the preemption layer) calls
  :meth:`Supervisor.shutdown`, which SIGTERMs every child (each drains
  through its own preemption handler) and only SIGKILLs stragglers.

- **elasticity**: :meth:`Supervisor.add_slot` grows the fleet by one
  supervised worker (router registration at weight 0 first, then the
  normal announce → ``/readyz`` handshake lifts it to full weight,
  warm through the shared compile cache and pinned to its own disjoint
  chip slot) and :meth:`Supervisor.retire_slot` shrinks it gracefully
  (weight→0, SIGTERM drain, SIGKILL stragglers past
  ``serving.drain_timeout_s``, state + breaker cleaned up). These are
  the process-level actuators the autopilot's scale lever drives
  through :class:`~mmlspark_tpu.serve.fleet.ProcessFleet`.

Decisions are observable: ``supervisor.spawn|ready|exit|backoff|restart|
giveup|add_slot|retire|retire_noop|shutdown`` events flow into the event
log / flight recorder and the report's supervisor section. Clock and
sleep are injectable so the whole restart state machine runs under a
virtual clock in tests.

Lint Rule 12 makes this module the ONE home for process management
(``subprocess.Popen``, ``os.kill``, ``os.waitpid``) in the package.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.reliability.breaker import CircuitBreaker
from mmlspark_tpu.reliability.retry import RetryPolicy
from mmlspark_tpu.serve.router import HttpReplica, ReplicaUnavailable
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.supervisor")


class ProcessWorker:
    """One spawned ``mmlspark-tpu serve`` child process.

    Satisfies the duck-typed worker-handle protocol the
    :class:`Supervisor` supervises (``pid``, ``addr``, ``poll``,
    ``terminate``, ``kill``, ``wait``). A daemon reader thread captures
    the child's one-line JSON announce (``{"serving": "host:port", ...}``)
    and then keeps draining stdout so the pipe never blocks the child.
    """

    def __init__(self, name: str, argv: Sequence[str],
                 env: Optional[Dict[str, str]] = None,
                 log_path: Optional[str] = None,
                 popen: Optional[Callable] = None):
        self.name = name
        self.addr = ""
        self.announce: Dict[str, object] = {}
        self._announced = threading.Event()
        self._log_fh = open(log_path, "ab") if log_path else None
        stderr = self._log_fh if self._log_fh is not None \
            else subprocess.DEVNULL
        # ``popen`` is the transport seam: the multi-host launcher wraps
        # the argv in an ssh invocation while reusing this class's
        # announce-handshake and drain machinery unchanged
        launch = popen if popen is not None else subprocess.Popen
        self.proc = launch(
            list(argv), env=env, stdout=subprocess.PIPE, stderr=stderr,
            text=True)
        self.pid = self.proc.pid
        self._reader = threading.Thread(
            target=self._drain_stdout,
            name=f"mmlspark-tpu-worker-{name}-stdout", daemon=True)
        self._reader.start()

    def _drain_stdout(self) -> None:
        try:
            first = self.proc.stdout.readline()
            try:
                info = json.loads(first)
                if isinstance(info, dict):
                    self.announce = info
                    self.addr = str(info.get("serving", ""))
            except (json.JSONDecodeError, TypeError):
                logger.warning("worker %s: unparseable announce %r",
                               self.name, first[:200])
            self._announced.set()
            for _ in self.proc.stdout:
                pass  # keep the pipe drained; content is the child's log
        except (OSError, ValueError):
            pass  # pipe torn down under us: the child died, poll() reaps
        finally:
            self._announced.set()

    def await_announce(self, timeout: float) -> bool:
        """Wait for the child's announce line; True iff an addr arrived."""
        self._announced.wait(timeout)
        return bool(self.addr)

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def terminate(self) -> None:
        """SIGTERM: the child's preemption handler drains gracefully."""
        try:
            self.proc.terminate()
        except OSError:
            pass  # already reaped

    def kill(self) -> None:
        """SIGKILL — the host-failure simulation: no drain, no goodbye."""
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass  # already dead; chaos double-kills under race

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            rc = self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None
        self.close()
        return rc

    def close(self) -> None:
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            except OSError:
                pass
            self._log_fh = None


class ProcessSpawner:
    """Factory for :class:`ProcessWorker` children.

    Builds the ``python -m mmlspark_tpu.cli serve`` command line: port 0
    (the child announces its real ephemeral port), ``--events-dir`` so
    every child writes its own ``events-<pid>.jsonl`` sidecar, and the
    shared compile-cache directory exported through the environment so
    restarts load programs instead of compiling them. The package root is
    prepended to ``PYTHONPATH`` so children import the same tree the
    supervisor runs from, and ``PYTHONUNBUFFERED`` guarantees the
    announce line crosses the pipe immediately.
    """

    def __init__(self, model_flags: Sequence[str], *,
                 host: str = "127.0.0.1",
                 events_dir: str = "",
                 compile_cache_dir: Optional[str] = None,
                 extra_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 devices_per_worker: int = 0):
        if not model_flags:
            raise ValueError("spawner needs at least one --model flag")
        self.model_flags = list(model_flags)
        self.host = host
        self.events_dir = events_dir
        self.compile_cache_dir = compile_cache_dir
        self.extra_args = list(extra_args)
        self.env = dict(env or {})
        self.devices_per_worker = int(devices_per_worker)
        # stable name -> slot assignment: a restarted replica keeps ITS
        # chips (first spawn claims the next slot, every respawn reuses
        # it), so two workers never share a chip across restarts
        self._slots: Dict[str, int] = {}

    def build_argv(self, name: str) -> List[str]:
        argv = [sys.executable, "-m", "mmlspark_tpu.cli", "serve",
                "--host", self.host, "--port", "0"]
        for spec in self.model_flags:
            argv += ["--model", spec]
        if self.events_dir:
            argv += ["--events-dir", self.events_dir]
        argv += self.extra_args
        return argv

    def slot_of(self, name: str) -> int:
        """The worker's stable slot index (assigned at first spawn)."""
        slot = self._slots.get(name)
        if slot is None:
            slot = len(self._slots)
            self._slots[name] = slot
        return slot

    def device_env(self, name: str) -> Dict[str, str]:
        """Per-worker accelerator pinning: with ``devices_per_worker=K``,
        slot ``i`` sees chips ``[i*K, (i+1)*K)`` — disjoint visible-device
        sets, so N single-host workers split the host's chips instead of
        all fighting over chip 0 (the JAX default when every process sees
        every device). Exported in every runtime's spelling; platforms
        ignore the vars they don't read. 0 = no pinning (workers share)."""
        k = self.devices_per_worker
        if k <= 0:
            return {}
        chips = ",".join(str(self.slot_of(name) * k + j) for j in range(k))
        return {"TPU_VISIBLE_CHIPS": chips,
                "CUDA_VISIBLE_DEVICES": chips,
                "HIP_VISIBLE_DEVICES": chips}

    def build_env(self, name: Optional[str] = None) -> Dict[str, str]:
        from mmlspark_tpu import compile_cache
        env = dict(os.environ)
        import mmlspark_tpu as _pkg
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(_pkg.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep \
            + env.get("PYTHONPATH", "") if env.get("PYTHONPATH") \
            else pkg_root
        env["PYTHONUNBUFFERED"] = "1"
        env.update(compile_cache.worker_env(self.compile_cache_dir))
        if name is not None:
            env.update(self.device_env(name))
        env.update(self.env)
        return env

    def spawn(self, name: str) -> ProcessWorker:
        log_path = None
        if self.events_dir:
            os.makedirs(self.events_dir, exist_ok=True)
            log_path = os.path.join(self.events_dir, f"worker-{name}.log")
        return ProcessWorker(name, self.build_argv(name),
                             env=self.build_env(name), log_path=log_path)


class _ReplicaState:
    """Supervisor-side lifecycle state for one replica slot."""

    __slots__ = ("name", "replica", "handle", "started_at", "confirmed",
                 "consecutive", "spawns", "ready_spawns", "next_restart_at",
                 "saved_weight", "gave_up_emitted")

    def __init__(self, name: str, replica: HttpReplica):
        self.name = name
        self.replica = replica
        self.handle = None
        self.started_at = 0.0
        self.confirmed = False       # survived min_uptime this incarnation
        self.consecutive = 0         # crashes since the last confirmed run
        self.spawns = 0
        self.ready_spawns = 0        # incarnations that reached _on_ready
        self.next_restart_at: Optional[float] = None
        self.saved_weight = 1.0
        self.gave_up_emitted = False


def _default_ready(replica: HttpReplica, handle) -> bool:
    try:
        return replica.probe_readyz()
    except ReplicaUnavailable:
        return False


class Supervisor:
    """Restart-on-crash supervision of N replica worker processes.

    One :class:`HttpReplica` object per slot is created at construction
    (placeholder addr until the first announce) — hand ``sup.replicas``
    to the :class:`Router` and :meth:`attach_router` back, and restarts
    re-register transparently: same object, same name, new addr.

    The restart state machine is pure against ``clock``/``sleep`` (both
    injectable) and is stepped by :meth:`poll_once`; :meth:`start_monitor`
    runs it on a daemon thread for real deployments. ``spawner`` is any
    object with ``spawn(name) -> handle``; tests inject fakes, production
    uses :class:`ProcessSpawner`.
    """

    def __init__(self, spawner, names: Sequence[str], *,
                 router=None,
                 min_uptime_s: Optional[float] = None,
                 base_delay_s: Optional[float] = None,
                 max_delay_s: Optional[float] = None,
                 ready_timeout_s: Optional[float] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 ready_fn: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        if not names:
            raise ValueError("supervisor needs at least one replica name")
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names in {list(names)!r}")
        self.spawner = spawner
        self.clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self.min_uptime_s = float(
            min_uptime_s if min_uptime_s is not None
            else mmlconfig.get("fleet.supervisor_min_uptime_s"))
        self.ready_timeout_s = float(
            ready_timeout_s if ready_timeout_s is not None
            else mmlconfig.get("fleet.supervisor_ready_timeout_s"))
        base = float(base_delay_s if base_delay_s is not None
                     else mmlconfig.get("fleet.supervisor_base_delay_s"))
        cap = float(max_delay_s if max_delay_s is not None
                    else mmlconfig.get("fleet.supervisor_max_delay_s"))
        # only .delay(attempt) is used: the supervisor schedules restarts
        # on its own clock instead of sleeping inside a policy loop, so a
        # crash-looper's growing backoff never blocks the other replicas
        self._backoff = RetryPolicy(
            max_attempts=1_000_000, base_delay=base, max_delay=cap,
            jitter=0.0, name="supervisor.backoff", clock=self.clock)
        failures = int(
            breaker_failures if breaker_failures is not None
            else mmlconfig.get("fleet.supervisor_breaker_failures"))
        reset_s = float(
            breaker_reset_s if breaker_reset_s is not None
            else mmlconfig.get("fleet.supervisor_breaker_reset_s"))
        self._breaker_failures = failures
        self._breaker_reset_s = reset_s
        self.breakers: Dict[str, CircuitBreaker] = {
            n: CircuitBreaker(f"supervisor.{n}", failure_threshold=failures,
                              reset_timeout_s=reset_s, clock=self.clock)
            for n in names}
        self.replicas: List[HttpReplica] = [
            HttpReplica("127.0.0.1:0", name=n) for n in names]
        self._states: Dict[str, _ReplicaState] = {
            n: _ReplicaState(n, r) for n, r in zip(names, self.replicas)}
        self.router = router
        self._ready_fn = ready_fn if ready_fn is not None else _default_ready
        self._lock = threading.Lock()
        self._closed = False
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        self._restarts = metrics.counter("supervisor.restarts")
        # elasticity bookkeeping: spawn->ready latencies (ms, most recent
        # first-in) and the names currently mid-retire, both surfaced by
        # stats() for the dashboard/report elasticity panel
        self._ready_ms: List[float] = []
        self._retiring: set = set()

    # -- wiring -------------------------------------------------------------
    def attach_router(self, router) -> None:
        """Give restarts a Router to re-register with (weight restore +
        breaker reset + probe). The Router was necessarily built AFTER
        the replicas it routes to."""
        self.router = router

    def replica(self, name: str) -> HttpReplica:
        return self._states[name].replica

    def breaker_state(self, name: str) -> str:
        return self.breakers[name].state

    def pid(self, name: str) -> Optional[int]:
        h = self._states[name].handle
        return h.pid if h is not None else None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        """Spawn every replica once. A slot that fails to come ready is
        left to the normal crash accounting in :meth:`poll_once` — start
        never raises for one bad replica."""
        for st in self._states.values():
            self._spawn(st)

    def _spawn(self, st: _ReplicaState) -> bool:
        st.handle = self.spawner.spawn(st.name)
        st.started_at = self.clock()
        st.confirmed = False
        st.spawns += 1
        st.next_restart_at = None
        st.gave_up_emitted = False
        logger.info("spawned replica %s pid=%s attempt=%d",
                    st.name, getattr(st.handle, "pid", None), st.spawns)
        if events.recording_enabled():
            events.emit("supervisor", "spawn", replica=st.name,
                        pid=getattr(st.handle, "pid", None),
                        attempt=st.spawns)
        if not self._wait_ready(st):
            # either the child already died (poll_once reaps and schedules
            # the backoff) or it wedged before ready — kill the wedge so
            # the crash accounting sees a clean exit
            if st.handle is not None and st.handle.poll() is None:
                st.handle.kill()
                st.handle.wait(5.0)
            return False
        self._on_ready(st)
        return True

    def _wait_ready(self, st: _ReplicaState) -> bool:
        deadline = self.clock() + self.ready_timeout_s
        h = st.handle
        if hasattr(h, "await_announce"):
            if not h.await_announce(self.ready_timeout_s):
                return False
        if getattr(h, "addr", ""):
            addr = str(h.addr)
            st.replica.addr = addr if "://" in addr else "http://" + addr
        while self.clock() < deadline:
            if h.poll() is not None:
                return False
            try:
                if self._ready_fn(st.replica, h):
                    return True
            except ReplicaUnavailable:
                pass  # restart window: refused connections are expected
            self._sleep(0.05)
        return False

    def _on_ready(self, st: _ReplicaState) -> None:
        if self.router is not None:
            self.router.set_weight(st.name, st.saved_weight)
            self.router.reset_breaker(st.name)
            try:
                self.router.probe()
            except Exception as e:  # probe must not kill supervision
                logger.warning("post-restart probe failed: %s", e)
        ready_ms = (self.clock() - st.started_at) * 1e3
        self._ready_ms.append(round(ready_ms, 3))
        del self._ready_ms[:-64]   # bounded: the last 64 scale/restart events
        if events.recording_enabled():
            events.emit("supervisor", "ready", replica=st.name,
                        pid=getattr(st.handle, "pid", None),
                        attempt=st.spawns,
                        spawn_to_ready_ms=round(ready_ms, 3))
        if st.spawns > 1:
            self._restarts.inc()
            ready_s = self.clock() - st.started_at
            logger.info("replica %s restarted warm pid=%s in %.2fs",
                        st.name, getattr(st.handle, "pid", None), ready_s)
            if events.recording_enabled():
                events.emit("supervisor", "restart", replica=st.name,
                            pid=getattr(st.handle, "pid", None),
                            attempt=st.spawns, ready_s=round(ready_s, 4))
        # bumped LAST: a stats() reader seeing ready_spawns == spawns
        # knows the CURRENT incarnation's addr and router registration
        # are already in place (stats() deliberately skips the lock so
        # it stays responsive while _wait_ready rides out a cold start)
        st.ready_spawns = st.spawns

    def poll_once(self) -> None:
        """One supervision step: reap exits, confirm uptimes, schedule
        and perform restarts. Deterministic against the injected clock."""
        with self._lock:
            if self._closed:
                return
            for st in self._states.values():
                self._poll_replica(st)

    def _poll_replica(self, st: _ReplicaState) -> None:
        now = self.clock()
        h = st.handle
        if h is not None:
            rc = h.poll()
            if rc is None:
                if not st.confirmed \
                        and now - st.started_at >= self.min_uptime_s:
                    # survived the min uptime: this incarnation is healthy
                    st.confirmed = True
                    st.consecutive = 0
                    self.breakers[st.name].record_success()
                return
            self._on_exit(st, h, rc, now)
            return
        if st.next_restart_at is None or now < st.next_restart_at:
            return
        if not self.breakers[st.name].allow():
            if not st.gave_up_emitted:
                st.gave_up_emitted = True
                logger.warning(
                    "replica %s crash-looping (%d consecutive); breaker "
                    "%s — holding out of rotation", st.name,
                    st.consecutive, self.breakers[st.name].state)
                if events.recording_enabled():
                    events.emit("supervisor", "giveup", replica=st.name,
                                consecutive=st.consecutive,
                                breaker=self.breakers[st.name].state)
            return
        self._spawn(st)

    def _on_exit(self, st: _ReplicaState, h, rc: int, now: float) -> None:
        uptime = now - st.started_at
        st.handle = None
        if hasattr(h, "close"):
            h.close()
        st.consecutive += 1
        self.breakers[st.name].record_failure()
        if self.router is not None:
            w = self.router.stats()["replicas"].get(
                st.name, {}).get("weight", 1.0)
            if w and w > 0:
                st.saved_weight = float(w)
            self.router.set_weight(st.name, 0.0)
        delay = self._backoff.delay(st.consecutive)
        st.next_restart_at = now + delay
        st.gave_up_emitted = False
        logger.warning(
            "replica %s pid=%s exited rc=%s after %.2fs; restart in %.2fs "
            "(crash %d)", st.name, getattr(h, "pid", None), rc, uptime,
            delay, st.consecutive)
        if events.recording_enabled():
            events.emit("supervisor", "exit", replica=st.name,
                        pid=getattr(h, "pid", None), returncode=rc,
                        uptime_s=round(uptime, 4))
            events.emit("supervisor", "backoff", replica=st.name,
                        attempt=st.consecutive, delay_s=round(delay, 4))

    # -- chaos lever --------------------------------------------------------
    def kill_replica(self, name: str) -> Optional[int]:
        """SIGKILL one child — the host-failure chaos lever. Returns the
        pid killed, or None when the slot has no live process (idempotent:
        the host scenario double-kills under race)."""
        st = self._states[name]
        h = st.handle
        if h is None or h.poll() is not None:
            return None
        pid = getattr(h, "pid", None)
        h.kill()
        return pid

    # -- elasticity ---------------------------------------------------------
    def _next_name(self) -> str:
        """Auto-name for a new slot: the smallest ``w<i>`` not in use.
        Caller holds ``self._lock``."""
        i = 0
        while f"w{i}" in self._states or f"w{i}" in self._retiring:
            i += 1
        return f"w{i}"

    def add_slot(self, name: Optional[str] = None) -> str:
        """Grow the fleet by one supervised worker process.

        Registers a fresh :class:`HttpReplica` with the router at weight
        0.0 FIRST (so the restart machinery's weight/breaker calls always
        find the name), then spawns through the normal announce-handshake
        path — :meth:`_on_ready` lifts the weight to 1.0 once ``/readyz``
        answers. A spawn that dies mid-handshake is reconciled by the
        ordinary supervision loop: :meth:`poll_once` reaps it, schedules
        the backoff, and respawns — the slot is never half-registered.
        Returns the new slot's name.
        """
        with self._lock:
            if self._closed:
                raise RuntimeError("supervisor is shut down")
            if name is None:
                name = self._next_name()
            if name in self._states:
                raise ValueError(f"replica name {name!r} already in use")
            rep = HttpReplica("127.0.0.1:0", name=name)
            st = _ReplicaState(name, rep)
            self.breakers[name] = CircuitBreaker(
                f"supervisor.{name}",
                failure_threshold=self._breaker_failures,
                reset_timeout_s=self._breaker_reset_s,
                clock=self.clock)
            self._states[name] = st
            self.replicas.append(rep)
        if events.recording_enabled():
            events.emit("supervisor", "add_slot", replica=name,
                        desired=len(self._states))
        logger.info("adding slot %s (desired=%d)", name, len(self._states))
        if self.router is not None:
            self.router.add_replica(rep, weight=0.0)
        self._spawn(st)
        if self._closed and st.handle is not None:
            st.handle.terminate()   # lost the race with shutdown()
        return name

    def retire_slot(self, name: str,
                    drain_timeout_s: Optional[float] = None) -> bool:
        """Shrink the fleet by one worker, gracefully.

        Weight goes to 0 first (no new requests land), then SIGTERM lets
        the child drain through its own preemption handler, SIGKILL
        reaps stragglers past ``serving.drain_timeout_s``, and finally
        the slot's router registration, state, and breaker are removed.
        Idempotent: an unknown or already-retired name emits a
        ``retire_noop`` event and returns False — the autopilot racing a
        crash may double-retire, and that must not throw inside the
        control loop.
        """
        with self._lock:
            st = self._states.get(name)
            if st is None or self._closed:
                if events.recording_enabled():
                    events.emit("supervisor", "retire_noop", replica=name)
                logger.info("retire_slot(%r): no such live slot", name)
                return False
            del self._states[name]
            self._retiring.add(name)
        try:
            if self.router is not None:
                try:
                    self.router.set_weight(name, 0.0)
                except KeyError:
                    pass  # never registered (spawn still in flight)
            h = st.handle
            drained = True
            if h is not None and h.poll() is None:
                timeout = float(
                    drain_timeout_s if drain_timeout_s is not None
                    else mmlconfig.get("serving.drain_timeout_s"))
                h.terminate()
                if h.wait(max(timeout, 0.0)) is None:
                    drained = False
                    logger.warning(
                        "slot %s did not drain in %.1fs; killing",
                        name, timeout)
                    h.kill()
                    h.wait(5.0)
            if h is not None and hasattr(h, "close"):
                h.close()
            if self.router is not None:
                try:
                    self.router.remove_replica(name)
                except KeyError:
                    pass  # never registered
                except ValueError:
                    # last replica: the router refuses to go empty; the
                    # slot stays registered at weight 0 (out of rotation)
                    logger.warning(
                        "slot %s is the router's last replica; left "
                        "registered at weight 0", name)
            with self._lock:
                if st.replica in self.replicas:
                    self.replicas.remove(st.replica)
                self.breakers.pop(name, None)
        finally:
            self._retiring.discard(name)
        if events.recording_enabled():
            events.emit("supervisor", "retire", replica=name,
                        drained=drained, desired=len(self._states))
        logger.info("retired slot %s (drained=%s, desired=%d)",
                    name, drained, len(self._states))
        return True

    # -- monitor thread -----------------------------------------------------
    def start_monitor(self, poll_s: Optional[float] = None) -> None:
        if self._monitor is not None:
            return
        poll = float(poll_s if poll_s is not None
                     else mmlconfig.get("fleet.supervisor_poll_s"))

        def run() -> None:
            while not self._monitor_stop.wait(poll):
                try:
                    self.poll_once()
                except Exception as e:  # supervision outlives one bad round
                    logger.warning("supervision round failed: %s", e)

        self._monitor = threading.Thread(
            target=run, name="mmlspark-tpu-supervisor", daemon=True)
        self._monitor.start()

    def stop_monitor(self) -> None:
        if self._monitor is None:
            return
        self._monitor_stop.set()
        self._monitor.join(timeout=10)
        self._monitor = None
        self._monitor_stop = threading.Event()

    # -- drain --------------------------------------------------------------
    def shutdown(self, reason: str = "shutdown",
                 drain_timeout_s: Optional[float] = None) -> None:
        """SIGTERM every child (each drains through its own preemption
        handler), SIGKILL stragglers past the drain budget, and stop
        restarting. Idempotent — the preemption monitor and the CLI's
        finally block may both call it."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.stop_monitor()
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else mmlconfig.get("serving.drain_timeout_s"))
        live = [st for st in self._states.values()
                if st.handle is not None and st.handle.poll() is None]
        for st in self._states.values():
            st.next_restart_at = None
        for st in live:
            st.handle.terminate()
        deadline = self.clock() + max(timeout, 0.0)
        for st in live:
            budget = max(deadline - self.clock(), 0.0)
            if st.handle.wait(budget) is None:
                logger.warning("replica %s did not drain in %.1fs; killing",
                               st.name, timeout)
                st.handle.kill()
                st.handle.wait(5.0)
        if events.recording_enabled():
            events.emit("supervisor", "shutdown", reason=reason,
                        workers=len(live))
        logger.info("supervisor shut down (%s): %d worker(s) stopped",
                    reason, len(live))

    def stats(self) -> Dict[str, object]:
        """Per-replica lifecycle stats plus the fleet-level
        ``desired_replicas`` vs ``live_replicas`` pair — the gap between
        "what the supervisor is supposed to keep running" and "what is
        actually up right now" that scale decisions are judged by."""
        # lock-free on purpose (see _on_ready); add_slot/retire_slot can
        # resize the dict mid-iteration, so snapshot with a short retry
        states: List[_ReplicaState] = []
        for _ in range(8):
            try:
                states = list(self._states.values())
                break
            except RuntimeError:   # dict changed size during iteration
                continue
        reps: Dict[str, object] = {}
        for st in states:
            h = st.handle
            breaker = self.breakers.get(st.name)
            reps[st.name] = {
                "pid": getattr(h, "pid", None) if h is not None else None,
                "running": h is not None and h.poll() is None,
                "spawns": st.spawns,
                "ready_spawns": st.ready_spawns,
                "consecutive_crashes": st.consecutive,
                "breaker": breaker.state if breaker is not None
                else "retired",
                "addr": st.replica.addr,
            }
        ready_ms = sorted(self._ready_ms)
        n = len(ready_ms)

        def _pct(p: float) -> float:
            if not n:
                return 0.0
            return ready_ms[min(n - 1, max(0, int(p / 100.0 * n + 0.5) - 1))]

        return {
            "replicas": reps,
            "desired_replicas": len(states),
            "live_replicas": sum(1 for r in reps.values()
                                 if r["running"]),
            # elasticity: slots mid-spawn (handle live but the current
            # incarnation not yet through _on_ready) / mid-retire, plus
            # the spawn->ready latency distribution over the last 64
            "spawns_in_flight": sum(
                1 for st in states
                if st.handle is not None and st.handle.poll() is None
                and st.ready_spawns < st.spawns),
            "retiring": len(self._retiring),
            "spawn_to_ready_ms": {
                "count": n,
                "p50": round(_pct(50), 3),
                "p99": round(_pct(99), 3),
                "max": round(ready_ms[-1], 3) if n else 0.0,
            },
        }

    def __enter__(self) -> "Supervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
