"""Generative serving lane: continuous batching over a paged KV arena.

The ``/score`` lane batches REQUESTS — admit, coalesce, one program call,
respond. Autoregressive generation cannot ride that shape: one request is
hundreds of sequential single-token steps, and naive request-batching
either runs each sequence alone (device idle at batch 1) or locks a batch
together until its LONGEST member finishes (finished sequences pad along,
waiting prompts starve). This module is the decode-native lane:

- :class:`GenerativeEntry` — the compiled half. One **prefill** program
  per prompt-length bucket (the full flax module ``apply`` with KV rows
  captured and scattered into the arena, so prefill numerics are the
  served model's numerics by construction) and ONE single-token **decode**
  program per batch-size bucket (hand-written forward over gathered KV
  pages, numerically mirroring the module). All programs AOT-compile
  through :meth:`GenerativeEntry._compile` — the generative twin of
  ``ModelEntry._compile`` — into the persistent program cache, so a warm
  replica restart pays ZERO compiles.
- :class:`ContinuousBatcher` — the policy half, pure logic like
  ``MicroBatcher``: sequences JOIN the in-flight batch the step a slot
  frees and LEAVE the step they finish; nobody waits for anyone else's
  completion.
- :class:`GenerateLane` — the executor half: a single thread owning the
  arena; each pass admits joiners (prefill + first sampled token = TTFT),
  then runs one bucketed decode step over the whole active set.

Admission reserves a sequence's FULL block budget (prompt + max-new) up
front from the :class:`~mmlspark_tpu.serve.kvcache.KVCacheManager`; when
the free list cannot cover it the request sheds with a retryable
``ServerOverloaded`` — decode never OOMs mid-flight and the fleet router
retries elsewhere. Sampling (greedy, temperature/top-k) is seeded per
(seed, position), so a failover RESTART from the prompt on a surviving
replica replays the exact token stream.

Decode steps donate the arena buffers (in-place on TPU); the arena's
attention runs the same fused Pallas flash path as scoring on real chips
(prefill attention goes through ``full_attention`` inside the module) and
the jnp reference on the CPU test mesh.

Four compounding raw-speed attacks ride the same seams (all
config-gated, all compiled through :meth:`GenerativeEntry._compile` so a
warm restart still pays zero XLA compiles):

- **Shared-prefix KV reuse** (``generate.prefix_cache``): admission
  hashes the prompt's full blocks (chained — see
  :func:`~mmlspark_tpu.serve.kvcache.prefix_block_hashes`) and
  ``KVCacheManager.try_reserve`` shares already-cached blocks, so N
  requests behind one system prompt pay prefill ONCE; only the uncached
  suffix runs through the **chunk** program. A full-prompt hit schedules
  a copy-on-write of the final block (no block is ever written while
  shared) and recomputes just the last position for its first token.
- **Chunked prefill** (``generate.prefill_chunk``): long prompts split
  into fixed-width chunks processed one per lane step, interleaved with
  decode — a long joiner never stalls the running batch's ITL.
- **Speculative decoding** (``generate.draft_model`` +
  ``generate.spec_tokens``): a small draft model (its own
  :class:`GenerativeEntry` + arena) proposes k tokens per step; the
  target checks them in ONE **verify** program call (the decode spec
  widened to k+1 positions). Accept/reject replays the exact
  per-(seed, position) sampler, so greedy AND seeded-sampling outputs
  are token-identical to the non-speculative lane by construction.
- **int8 KV blocks** (``generate.kv_dtype=int8``): the arena stores
  quantized rows (~2x concurrent-sequence capacity at fixed bytes);
  dequantization is fused into the decode/verify/chunk programs via the
  helpers in ``kvcache.py`` (lint Rule 13 keeps scale math there).
"""
from __future__ import annotations

import math
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.observability import events, metrics, spans, syncs
from mmlspark_tpu.reliability import watchdog as _watchdog
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.serve.batcher import bucket_for, default_buckets
from mmlspark_tpu.serve.kvcache import (
    RESERVED_BLOCK, KVCacheManager, blocks_needed, dequantize_rows,
    prefix_block_hashes, quantize_rows,
)
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.generate")

_STOP = object()


# ---------------------------------------------------------------------------
# buckets


def parse_prefill_buckets(text: str, max_seq_len: int,
                          block_tokens: int) -> Tuple[int, ...]:
    """``generate.prefill_buckets`` config -> ascending bucket tuple.
    Every bucket must be a multiple of ``block_tokens`` (prefill scatters
    whole blocks) and the ladder must cover ``max_seq_len``. "" derives
    powers of two from ``block_tokens`` up to ``max_seq_len``."""
    if text.strip():
        vals = sorted({int(v) for v in text.split(",") if v.strip()})
    else:
        vals, b = [], block_tokens
        while b < max_seq_len:
            vals.append(b)
            b *= 2
        vals.append(b)
    bad = [v for v in vals if v < 1 or v % block_tokens]
    if bad:
        raise ValueError(
            f"prefill buckets must be positive multiples of "
            f"kv_block_tokens={block_tokens}, got {bad}")
    if vals[-1] < max_seq_len:
        raise ValueError(
            f"largest prefill bucket {vals[-1]} < max_seq_len "
            f"{max_seq_len}; the longest admissible prompt would have no "
            "compiled shape")
    return tuple(vals)


# ---------------------------------------------------------------------------
# sampling — host-side, deterministic per (seed, position) so a failover
# restart from the prompt replays the identical token stream


def sample_token(logits: np.ndarray, *, temperature: float, top_k: int,
                 seed: int, position: int) -> int:
    """One next-token draw from a (vocab,) logits row. ``temperature <= 0``
    is greedy (pure argmax, no RNG at all); otherwise top-k + temperature
    with an RNG derived from (seed, position) — the same (seed, position)
    always yields the same token regardless of replica or retry."""
    if temperature <= 0:
        return int(np.argmax(logits))
    scaled = logits.astype(np.float64) / float(temperature)
    if top_k > 0 and top_k < scaled.size:
        cutoff = np.partition(scaled, -top_k)[-top_k]
        scaled = np.where(scaled < cutoff, -np.inf, scaled)
    scaled = scaled - scaled.max()
    p = np.exp(scaled)
    p /= p.sum()
    rng = np.random.default_rng((int(seed) & 0x7FFFFFFF, int(position)))
    return int(rng.choice(p.size, p=p))


# ---------------------------------------------------------------------------
# numerics mirrored from models/zoo/transformer.py — the decode program
# recomputes the module's math one token at a time. Flax formulas are
# reproduced exactly (LayerNorm's clamped variance, tanh-approximate gelu,
# fp32 norms and logits) so greedy decode is token-identical to a full
# forward pass of the same sequence.


def _layer_norm(x, scale, bias, eps: float = 1e-6):
    xf = x.astype(np.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    mean2 = (xf * xf).mean(axis=-1, keepdims=True)
    import jax
    import jax.numpy as jnp
    var = jnp.maximum(0.0, mean2 - mean * mean)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return y * scale.astype(np.float32) + bias.astype(np.float32)


def _dense(x, p, dtype):
    import jax.numpy as jnp
    return jnp.dot(x.astype(dtype), p["kernel"].astype(dtype)) \
        + p["bias"].astype(dtype)


# ---------------------------------------------------------------------------
# requests and sequences


@dataclass
class GenerateRequest:
    """One admitted generation ask (the ``/generate`` wire shape)."""
    model: str
    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    seed: int = 0
    eos_id: Optional[int] = None
    deadline_ms: Optional[float] = None
    trace_id: str = ""


class _Seq:
    """One in-flight sequence: prompt, sampled tokens, leased blocks, and
    the latency ledger (TTFT + inter-token gaps) its caller is owed."""

    __slots__ = ("seq_id", "prompt", "max_new", "temperature", "top_k",
                 "seed", "eos_id", "future", "trace_id", "enqueued",
                 "deadline", "generated", "ttft_s", "last_t", "itl_s",
                 "finish", "prefill_pos", "hashes", "spec_ok",
                 "spec_proposed", "spec_accepted", "prefix_hits",
                 "draft_hashes")

    def __init__(self, seq_id: str, req: GenerateRequest, future: Future,
                 enqueued: float, deadline: Optional[float]):
        self.seq_id = seq_id
        self.prompt = np.asarray(req.prompt, np.int32).ravel()
        self.max_new = int(req.max_new_tokens)
        self.temperature = float(req.temperature)
        self.top_k = int(req.top_k)
        self.seed = int(req.seed)
        self.eos_id = req.eos_id
        self.future = future
        self.trace_id = req.trace_id
        self.enqueued = enqueued
        self.deadline = deadline
        self.generated: List[int] = []
        self.ttft_s: Optional[float] = None
        self.last_t = enqueued
        self.itl_s: List[float] = []
        self.finish = ""
        self.prefill_pos = 0            # next prompt position to prefill
        self.hashes: List[str] = []     # chained full-block prefix hashes
        self.spec_ok = False            # draft arena reserved: may ride
        self.spec_proposed = 0          # speculation for this sequence
        self.spec_accepted = 0
        self.prefix_hits = 0            # prefix blocks shared at reserve
        self.draft_hashes: List[str] = []  # draft-arena prefix hashes

    @property
    def seq_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def result(self) -> Dict[str, Any]:
        itl = self.itl_s
        return {
            "tokens": list(self.generated),
            "finish_reason": self.finish,
            "ttft_ms": round((self.ttft_s or 0.0) * 1e3, 3),
            "itl_mean_ms": round(sum(itl) / len(itl) * 1e3, 3) if itl
            else 0.0,
            # prefix blocks shared at admission: the per-request ground
            # truth the fleet bench sums into its hit rate — a router
            # that CLAIMS affinity steered well is checked against what
            # the replica's arena actually re-used
            "prefix_hits": int(self.prefix_hits),
            "trace_id": self.trace_id,
        }


# ---------------------------------------------------------------------------
# continuous batching policy (pure logic, injectable clock, no threads)


class ContinuousBatcher:
    """The continuous-batching sibling of
    :class:`~mmlspark_tpu.serve.batcher.MicroBatcher`, speaking the same
    ``offer``/``ready``/``wait_s``/``take`` vocabulary so the executor
    loop reads identically — with one structural difference: ``take``
    admits JOINERS into a persistent ``active`` set (capped at
    ``max_sequences``) instead of flushing a transient group, and
    :meth:`leave` retires a finished sequence the same step it finishes,
    freeing its slot for the next waiter. Not thread-safe by itself; the
    lane's single executor thread is the only caller."""

    def __init__(self, max_sequences: int,
                 clock: Callable[[], float] = time.monotonic):
        if max_sequences < 1:
            raise ValueError(
                f"max_sequences must be >= 1, got {max_sequences}")
        self.max_sequences = int(max_sequences)
        self.clock = clock
        self._waiting: "deque[_Seq]" = deque()
        self._active: List[_Seq] = []

    def __len__(self) -> int:
        return len(self._waiting)

    @property
    def active(self) -> List[_Seq]:
        return list(self._active)

    @property
    def free_slots(self) -> int:
        return self.max_sequences - len(self._active)

    def offer(self, seq: _Seq) -> None:
        self._waiting.append(seq)

    def ready(self, now: Optional[float] = None) -> bool:
        """A step is due whenever anything is decoding or a waiter can
        join — continuous batching has no coalescing delay to wait out."""
        if self._active:
            return True
        return bool(self._waiting) and self.free_slots > 0

    def wait_s(self, now: Optional[float] = None) -> Optional[float]:
        return 0.0 if self.ready(now) else None

    def take(self, now: Optional[float] = None) -> List[_Seq]:
        """Pop the joiners for THIS step: FIFO waiters up to the free
        slots. The caller prefills each and confirms with :meth:`join`
        (or sheds/expires it without joining)."""
        out: List[_Seq] = []
        while self._waiting and len(self._active) + len(out) \
                < self.max_sequences:
            out.append(self._waiting.popleft())
        return out

    def requeue(self, seq: _Seq) -> None:
        """Put a taken-but-not-admitted waiter back at the FRONT of the
        queue (its slot this step went to a sequence still mid-chunked-
        prefill); it stays first in line for the next step."""
        self._waiting.appendleft(seq)

    def join(self, seq: _Seq) -> None:
        if len(self._active) >= self.max_sequences:
            raise ValueError("active set full; take() admitted too many")
        self._active.append(seq)

    def leave(self, seq: _Seq) -> None:
        self._active.remove(seq)

    def drain(self) -> List[_Seq]:
        """Everything still owned by the batcher (waiting + active), for
        shutdown paths. Leaves the batcher empty."""
        out = list(self._waiting) + list(self._active)
        self._waiting.clear()
        self._active.clear()
        return out


# ---------------------------------------------------------------------------
# compiled programs


class GenerativeEntry:
    """Compiled generative artifacts for one registered model: the KV
    arena plus bucketed prefill / decode executables.

    :meth:`_compile` is THE generative compile seam — the twin of
    ``ModelEntry._compile`` that tests wrap to assert one compile per
    (kind, bucket) — and it funnels through
    :func:`mmlspark_tpu.compile_cache.load_or_compile_program`, so every
    program lands in the persistent on-disk cache and a warm replica
    restart loads instead of compiling. Real compiles and cache loads
    are accounted on the UNDERLYING ``ModelEntry`` (``compile_count`` /
    ``cache_hits``), so registry stats and the bench gate see scoring and
    generative compiles in one ledger.
    """

    def __init__(self, entry, *, max_seq_len: Optional[int] = None,
                 max_sequences: Optional[int] = None):
        self.entry = entry
        apply = entry.ensure_apply()
        # mesh-bound models decode too: params stay in their (tensor/fsdp)
        # placement and the KV arena below joins them on the same mesh, so
        # a model bigger than one chip's HBM serves the generative lane
        self.mesh = getattr(apply, "_mesh", None)
        spec = entry.model._spec()
        module = spec.get("module")
        for attr in ("vocab", "dim", "depth", "heads", "max_len"):
            if not hasattr(module, attr):
                raise ValueError(
                    f"model {entry.name!r} ({type(module).__name__}) is "
                    "not a decoder LM; the generative lane serves "
                    "TransformerLM-shaped architectures")
        self.module = module
        self.params = apply._params
        self.vocab = int(module.vocab)
        self.dim = int(module.dim)
        self.depth = int(module.depth)
        self.heads = int(module.heads)
        self.head_dim = self.dim // self.heads
        self.dtype = module.dtype
        cap = int(max_seq_len if max_seq_len is not None
                  else mmlconfig.get("generate.max_seq_len"))
        self.max_seq_len = min(cap, int(module.max_len))
        self.max_sequences = int(
            max_sequences if max_sequences is not None
            else mmlconfig.get("generate.max_sequences"))
        self.kv = KVCacheManager.from_config(
            layers=self.depth, heads=self.heads, head_dim=self.head_dim,
            dtype=np.dtype(self.dtype), mesh=self.mesh,
            shard_heads=bool(mmlconfig.get("generate.shard_kv")))
        self.block_tokens = self.kv.block_tokens
        # block-table width: every sequence's table is padded to the
        # blocks a max-length sequence needs, so ONE decode program shape
        # serves every occupancy
        self.table_width = blocks_needed(self.max_seq_len,
                                         self.block_tokens)
        self.prefill_buckets = parse_prefill_buckets(
            str(mmlconfig.get("generate.prefill_buckets")),
            self.max_seq_len, self.block_tokens)
        self.decode_buckets = default_buckets(self.max_sequences)
        self.prefix_cache = bool(mmlconfig.get("generate.prefix_cache"))
        self.prefill_chunk = max(0, int(mmlconfig.get(
            "generate.prefill_chunk")))
        # the chunk program's width: the configured chunk, else one block
        # (the chunk path also serves the uncached-SUFFIX prefill after a
        # prefix hit, so it exists even with chunking nominally off)
        self.chunk_width = min(self.max_seq_len,
                               self.prefill_chunk if self.prefill_chunk > 0
                               else self.block_tokens)
        self.spec_tokens = max(0, int(mmlconfig.get("generate.spec_tokens")))
        self.spec_width = self.spec_tokens + 1
        self._programs: Dict[Tuple[str, int], Callable] = {}
        # the arena is HBM this model now pins: charge it to the registry
        # entry so the device-cache LRU sees params + arena as one tenant.
        # PER-SHARD bytes: a head-sharded arena costs each chip 1/|tensor|
        # of the logical total, and that is what the budget must see.
        entry.kv_arena_bytes = self.kv.arena_shard_bytes()

    # -- compile seam ------------------------------------------------------
    def program_for(self, kind: str, bucket: int) -> Callable:
        key = (kind, int(bucket))
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile(kind, int(bucket))
            self._programs[key] = prog
        return prog

    def _compile(self, kind: str, bucket: int) -> Callable:
        """Build (or cache-load) the executable for one (kind, bucket).
        Every generative compilation funnels through here exactly once
        per key — the compile-discipline tests wrap this method."""
        from mmlspark_tpu import compile_cache
        if kind == "prefill":
            jitted, abstract = self._prefill_spec(bucket)
        elif kind == "decode":
            jitted, abstract = self._decode_spec(bucket)
        elif kind == "chunk":
            jitted, abstract = self._chunk_spec(bucket)
        elif kind == "verify":
            jitted, abstract = self._verify_spec(bucket)
        elif kind == "cow":
            jitted, abstract = self._cow_spec()
        else:
            raise ValueError(f"unknown program kind {kind!r}")
        shape_key = (f"{kind}:{bucket}|arena={self.kv.num_blocks}x"
                     f"{self.block_tokens}x{self.heads}x{self.head_dim}"
                     f"|layers={self.depth}|W={self.table_width}"
                     f"|dtype={self.kv.dtype.name}")
        if kind == "verify":
            shape_key += f"|C={self.spec_width}"
        if self.mesh is not None:
            # mesh identity: the same bucket lowered for a different
            # topology (or head-sharded vs replicated arena) is a
            # DIFFERENT executable — its input shardings are baked in
            axes = ",".join(f"{a}{n}" for a, n in self.mesh.shape.items()
                            if n > 1) or "1"
            spec = getattr(self.kv.arena_sharding, "spec", ())
            shape_key += f"|mesh={axes}|kvspec={tuple(spec)!r}"
        result = compile_cache.load_or_compile_program(
            self.entry.name, self.entry.version, kind, shape_key,
            jitted, self.params, *abstract)
        if result.hit:
            self.entry.cache_hits += 1
        else:
            self.entry.compile_count += 1
        return result.program

    def _arena_abstract(self):
        """The arena operand placeholders every program takes right after
        ``params`` — (k, v) plus the two fp32 scale planes when int8 —
        and the matching ``donate_argnums``. On a mesh the placeholders
        carry the arena's NamedSharding: an AOT-compiled executable
        rejects committed inputs whose sharding differs from what it was
        lowered with, so the placement must be part of the lowering."""
        import jax
        kv = self.kv
        if kv.mesh is not None:
            arena = jax.ShapeDtypeStruct(kv.arena_k.shape, kv.dtype,
                                         sharding=kv.arena_sharding)
            if kv.quantized:
                sc = jax.ShapeDtypeStruct(kv.scale_k.shape, np.float32,
                                          sharding=kv.scale_sharding)
                return (arena, arena, sc, sc), (1, 2, 3, 4)
            return (arena, arena), (1, 2)
        arena = jax.ShapeDtypeStruct(kv.arena_k.shape, kv.dtype)
        if kv.quantized:
            sc = jax.ShapeDtypeStruct(kv.scale_k.shape, np.float32)
            return (arena, arena, sc, sc), (1, 2, 3, 4)
        return (arena, arena), (1, 2)

    # -- prefill -----------------------------------------------------------
    def _prefill_spec(self, bucket: int):
        """Jitted prefill for one prompt-length bucket ``Lb``: run the
        module's OWN apply (prefill numerics are the served model's by
        construction), capture each block's K/V projections, scatter them
        into the sequence's arena blocks, and return the last live
        position's logits row."""
        import jax
        import jax.numpy as jnp
        module, depth = self.module, self.depth
        nb = bucket // self.block_tokens
        bt, heads, hd = self.block_tokens, self.heads, self.head_dim
        quant = self.kv.quantized

        def kv_filter(mdl, _method):
            return getattr(mdl, "name", None) in ("attn_key", "attn_value")

        def body(params, arena_k, arena_v, scale_k, scale_v, tokens,
                 last_pos, block_ids):
            logits, state = module.apply(
                params, tokens, capture_intermediates=kv_filter,
                mutable=["intermediates"])
            inter = state["intermediates"]
            ks = jnp.stack([inter[f"block{i}"]["attn_key"]["__call__"][0][0]
                            for i in range(depth)])
            vs = jnp.stack([inter[f"block{i}"]["attn_value"]["__call__"][0]
                            [0] for i in range(depth)])
            ks = ks.reshape(depth, nb, bt, heads, hd)
            vs = vs.reshape(depth, nb, bt, heads, hd)
            if quant:
                ks, sk = quantize_rows(ks)
                vs, sv = quantize_rows(vs)
                scale_k = scale_k.at[:, block_ids].set(sk)
                scale_v = scale_v.at[:, block_ids].set(sv)
            arena_k = arena_k.at[:, block_ids].set(ks)
            arena_v = arena_v.at[:, block_ids].set(vs)
            row = jnp.take(logits[0], last_pos, axis=0)
            return arena_k, arena_v, scale_k, scale_v, row

        if quant:
            def prefill(params, ak, av, sk, sv, tokens, last_pos, blocks):
                return body(params, ak, av, sk, sv, tokens, last_pos,
                            blocks)
        else:
            def prefill(params, ak, av, tokens, last_pos, blocks):
                ak, av, _sk, _sv, row = body(params, ak, av, None, None,
                                             tokens, last_pos, blocks)
                return ak, av, row

        arenas, donate = self._arena_abstract()
        jitted = jax.jit(prefill, donate_argnums=donate)  # lint: allow-compile
        abstract = arenas + (
            jax.ShapeDtypeStruct((1, bucket), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((nb,), np.int32),
        )
        return jitted, abstract

    # -- decode ------------------------------------------------------------
    def _decode_spec(self, batch: int):
        """Jitted single-token decode for one batch bucket ``B``: scatter
        each lane's new K/V into its pages, gather the paged history, and
        run one manually-unrolled forward step mirroring the module's
        math. Lanes without a live sequence (``seq_lens == 0``) write to
        the reserved scratch block and their logits are ignored host-side
        — the compiled program never branches on occupancy."""
        import jax
        import jax.numpy as jnp
        depth, heads, hd, dim = self.depth, self.heads, self.head_dim, \
            self.dim
        bt, W, dtype = self.block_tokens, self.table_width, self.dtype
        scale = 1.0 / np.sqrt(hd)
        quant = self.kv.quantized

        def body(params, arena_k, arena_v, scale_k, scale_v, tokens,
                 positions, block_tables, seq_lens):
            p = params.get("params", params)
            table = p["token_embedding"]["embedding"]
            x = jnp.take(table.astype(dtype), tokens, axis=0)
            x = x + jnp.take(p["pos_embedding"][0], positions,
                             axis=0).astype(x.dtype)
            active = seq_lens > 0
            blk_col = positions // bt
            blk_idx = jnp.take_along_axis(
                block_tables, blk_col[:, None], axis=1)[:, 0]
            blk_idx = jnp.where(active, blk_idx, RESERVED_BLOCK)
            offs = positions % bt
            idx = jnp.arange(W * bt)
            masked = idx[None, :] > positions[:, None]     # (B, K)
            for i in range(depth):
                blk = p[f"block{i}"]
                y = _layer_norm(x, blk["norm1"]["scale"],
                                blk["norm1"]["bias"])
                q = _dense(y, blk["attn_query"], dtype)
                k = _dense(y, blk["attn_key"], dtype)
                v = _dense(y, blk["attn_value"], dtype)
                qh = q.reshape(-1, heads, hd)
                kr = k.reshape(-1, heads, hd)
                vr = v.reshape(-1, heads, hd)
                # scatter FIRST so the current token attends itself
                if quant:
                    qk, ssk = quantize_rows(kr)
                    qv, ssv = quantize_rows(vr)
                    arena_k = arena_k.at[i, blk_idx, offs].set(qk)
                    arena_v = arena_v.at[i, blk_idx, offs].set(qv)
                    scale_k = scale_k.at[i, blk_idx, offs].set(ssk)
                    scale_v = scale_v.at[i, blk_idx, offs].set(ssv)
                    k_all = dequantize_rows(
                        arena_k[i][block_tables].reshape(
                            -1, W * bt, heads, hd),
                        scale_k[i][block_tables].reshape(
                            -1, W * bt)).astype(dtype)
                    v_all = dequantize_rows(
                        arena_v[i][block_tables].reshape(
                            -1, W * bt, heads, hd),
                        scale_v[i][block_tables].reshape(
                            -1, W * bt)).astype(dtype)
                else:
                    arena_k = arena_k.at[i, blk_idx, offs].set(kr)
                    arena_v = arena_v.at[i, blk_idx, offs].set(vr)
                    k_all = arena_k[i][block_tables].reshape(
                        -1, W * bt, heads, hd)
                    v_all = arena_v[i][block_tables].reshape(
                        -1, W * bt, heads, hd)
                s = jnp.einsum("bhd,bkhd->bhk", qh, k_all,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(masked[:, None, :], -jnp.inf, s)
                pr = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bhk,bkhd->bhd", pr.astype(v_all.dtype),
                               v_all,
                               preferred_element_type=jnp.float32)
                o = o.astype(qh.dtype)
                x = x + _dense(o.reshape(-1, dim), blk["attn_out"], dtype)
                y = _layer_norm(x, blk["norm2"]["scale"],
                                blk["norm2"]["bias"])
                h = _dense(y, blk["mlp_up"], dtype)
                h = jax.nn.gelu(h)
                x = x + _dense(h, blk["mlp_down"], dtype)
            xf = _layer_norm(x, p["final_norm"]["scale"],
                             p["final_norm"]["bias"])
            logits = jnp.einsum("bd,vd->bv", xf.astype(jnp.float32),
                                table.astype(jnp.float32))
            return arena_k, arena_v, scale_k, scale_v, logits

        if quant:
            def decode(params, ak, av, sk, sv, tokens, positions, tables,
                       seq_lens):
                return body(params, ak, av, sk, sv, tokens, positions,
                            tables, seq_lens)
        else:
            def decode(params, ak, av, tokens, positions, tables,
                       seq_lens):
                ak, av, _sk, _sv, out = body(params, ak, av, None, None,
                                             tokens, positions, tables,
                                             seq_lens)
                return ak, av, out

        arenas, donate = self._arena_abstract()
        jitted = jax.jit(decode, donate_argnums=donate)  # lint: allow-compile
        abstract = arenas + (
            jax.ShapeDtypeStruct((batch,), np.int32),
            jax.ShapeDtypeStruct((batch,), np.int32),
            jax.ShapeDtypeStruct((batch, W), np.int32),
            jax.ShapeDtypeStruct((batch,), np.int32),
        )
        return jitted, abstract

    # -- chunked / suffix prefill -----------------------------------------
    def _chunk_spec(self, C: int):
        """Jitted prefill CHUNK: ``C`` consecutive prompt positions of ONE
        sequence, scatter-first then gather like decode so positions
        within the chunk attend each other. Serves both chunked prefill
        (long prompts interleaved with decode) and the uncached-suffix
        prefill after a prefix-cache hit (``positions`` start at the
        first uncached token; earlier shared blocks are only READ).
        Invalid rows (``>= n_valid``) write to reserved scratch and their
        logits are ignored host-side."""
        import jax
        import jax.numpy as jnp
        depth, heads, hd, dim = self.depth, self.heads, self.head_dim, \
            self.dim
        bt, W, dtype = self.block_tokens, self.table_width, self.dtype
        scale = 1.0 / np.sqrt(hd)
        quant = self.kv.quantized

        def body(params, arena_k, arena_v, scale_k, scale_v, tokens,
                 positions, table_row, n_valid):
            p = params.get("params", params)
            table = p["token_embedding"]["embedding"]
            x = jnp.take(table.astype(dtype), tokens, axis=0)      # (C, d)
            x = x + jnp.take(p["pos_embedding"][0], positions,
                             axis=0).astype(x.dtype)
            valid = jnp.arange(C) < n_valid
            blk_idx = jnp.where(valid, jnp.take(table_row, positions // bt),
                                RESERVED_BLOCK)
            offs = positions % bt
            idx = jnp.arange(W * bt)
            masked = idx[None, :] > positions[:, None]     # (C, K) causal
            for i in range(depth):
                blk = p[f"block{i}"]
                y = _layer_norm(x, blk["norm1"]["scale"],
                                blk["norm1"]["bias"])
                q = _dense(y, blk["attn_query"], dtype)
                k = _dense(y, blk["attn_key"], dtype)
                v = _dense(y, blk["attn_value"], dtype)
                qh = q.reshape(C, heads, hd)
                kr = k.reshape(C, heads, hd)
                vr = v.reshape(C, heads, hd)
                if quant:
                    qk, ssk = quantize_rows(kr)
                    qv, ssv = quantize_rows(vr)
                    arena_k = arena_k.at[i, blk_idx, offs].set(qk)
                    arena_v = arena_v.at[i, blk_idx, offs].set(qv)
                    scale_k = scale_k.at[i, blk_idx, offs].set(ssk)
                    scale_v = scale_v.at[i, blk_idx, offs].set(ssv)
                    k_all = dequantize_rows(
                        arena_k[i][table_row].reshape(W * bt, heads, hd),
                        scale_k[i][table_row].reshape(W * bt)
                    ).astype(dtype)
                    v_all = dequantize_rows(
                        arena_v[i][table_row].reshape(W * bt, heads, hd),
                        scale_v[i][table_row].reshape(W * bt)
                    ).astype(dtype)
                else:
                    arena_k = arena_k.at[i, blk_idx, offs].set(kr)
                    arena_v = arena_v.at[i, blk_idx, offs].set(vr)
                    k_all = arena_k[i][table_row].reshape(
                        W * bt, heads, hd)
                    v_all = arena_v[i][table_row].reshape(
                        W * bt, heads, hd)
                s = jnp.einsum("chd,khd->chk", qh, k_all,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(masked[:, None, :], -jnp.inf, s)
                pr = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("chk,khd->chd", pr.astype(v_all.dtype),
                               v_all,
                               preferred_element_type=jnp.float32)
                o = o.astype(qh.dtype)
                x = x + _dense(o.reshape(C, dim), blk["attn_out"], dtype)
                y = _layer_norm(x, blk["norm2"]["scale"],
                                blk["norm2"]["bias"])
                h = _dense(y, blk["mlp_up"], dtype)
                h = jax.nn.gelu(h)
                x = x + _dense(h, blk["mlp_down"], dtype)
            xf = _layer_norm(x, p["final_norm"]["scale"],
                             p["final_norm"]["bias"])
            logits = jnp.einsum("cd,vd->cv", xf.astype(jnp.float32),
                                table.astype(jnp.float32))
            row = jnp.take(logits, jnp.maximum(n_valid - 1, 0), axis=0)
            return arena_k, arena_v, scale_k, scale_v, row

        if quant:
            def chunk(params, ak, av, sk, sv, tokens, positions, table_row,
                      n_valid):
                return body(params, ak, av, sk, sv, tokens, positions,
                            table_row, n_valid)
        else:
            def chunk(params, ak, av, tokens, positions, table_row,
                      n_valid):
                ak, av, _sk, _sv, row = body(params, ak, av, None, None,
                                             tokens, positions, table_row,
                                             n_valid)
                return ak, av, row

        arenas, donate = self._arena_abstract()
        jitted = jax.jit(chunk, donate_argnums=donate)  # lint: allow-compile
        abstract = arenas + (
            jax.ShapeDtypeStruct((C,), np.int32),
            jax.ShapeDtypeStruct((C,), np.int32),
            jax.ShapeDtypeStruct((W,), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
        )
        return jitted, abstract

    # -- speculative verify ------------------------------------------------
    def _verify_spec(self, batch: int):
        """Jitted speculative VERIFY for one batch bucket: the decode
        program widened to ``spec_width = spec_tokens + 1`` positions per
        lane. Row ``j`` of a lane's logits is the target model's
        next-token distribution after consuming fed token ``j`` — the
        host accepts draft proposals left to right while they match the
        target's own sampler, so the emitted stream is token-identical
        to non-speculative decode by construction. Lanes feed
        ``n_valid in [1, C]`` tokens (1 = plain decode riding the same
        program); rows past ``n_valid`` scatter to reserved scratch."""
        import jax
        import jax.numpy as jnp
        depth, heads, hd, dim = self.depth, self.heads, self.head_dim, \
            self.dim
        bt, W, dtype = self.block_tokens, self.table_width, self.dtype
        C = self.spec_width
        scale = 1.0 / np.sqrt(hd)
        quant = self.kv.quantized

        def body(params, arena_k, arena_v, scale_k, scale_v, tokens,
                 positions, block_tables, n_valid):
            p = params.get("params", params)
            table = p["token_embedding"]["embedding"]
            x = jnp.take(table.astype(dtype), tokens, axis=0)   # (B, C, d)
            x = x + jnp.take(p["pos_embedding"][0], positions,
                             axis=0).astype(x.dtype)
            valid = jnp.arange(C)[None, :] < n_valid[:, None]   # (B, C)
            blk_idx = jnp.take_along_axis(block_tables, positions // bt,
                                          axis=1)
            blk_idx = jnp.where(valid, blk_idx, RESERVED_BLOCK)
            offs = positions % bt
            idx = jnp.arange(W * bt)
            masked = idx[None, None, :] > positions[:, :, None]  # (B,C,K)
            for i in range(depth):
                blk = p[f"block{i}"]
                y = _layer_norm(x, blk["norm1"]["scale"],
                                blk["norm1"]["bias"])
                q = _dense(y, blk["attn_query"], dtype)
                k = _dense(y, blk["attn_key"], dtype)
                v = _dense(y, blk["attn_value"], dtype)
                qh = q.reshape(-1, C, heads, hd)
                kr = k.reshape(-1, C, heads, hd)
                vr = v.reshape(-1, C, heads, hd)
                # scatter the whole window FIRST: row j attends rows < j
                # of its own window through the arena, like decode
                if quant:
                    qk, ssk = quantize_rows(kr)
                    qv, ssv = quantize_rows(vr)
                    arena_k = arena_k.at[i, blk_idx, offs].set(qk)
                    arena_v = arena_v.at[i, blk_idx, offs].set(qv)
                    scale_k = scale_k.at[i, blk_idx, offs].set(ssk)
                    scale_v = scale_v.at[i, blk_idx, offs].set(ssv)
                    k_all = dequantize_rows(
                        arena_k[i][block_tables].reshape(
                            -1, W * bt, heads, hd),
                        scale_k[i][block_tables].reshape(
                            -1, W * bt)).astype(dtype)
                    v_all = dequantize_rows(
                        arena_v[i][block_tables].reshape(
                            -1, W * bt, heads, hd),
                        scale_v[i][block_tables].reshape(
                            -1, W * bt)).astype(dtype)
                else:
                    arena_k = arena_k.at[i, blk_idx, offs].set(kr)
                    arena_v = arena_v.at[i, blk_idx, offs].set(vr)
                    k_all = arena_k[i][block_tables].reshape(
                        -1, W * bt, heads, hd)
                    v_all = arena_v[i][block_tables].reshape(
                        -1, W * bt, heads, hd)
                s = jnp.einsum("bchd,bkhd->bchk", qh, k_all,
                               preferred_element_type=jnp.float32) * scale
                s = jnp.where(masked[:, :, None, :], -jnp.inf, s)
                pr = jax.nn.softmax(s, axis=-1)
                o = jnp.einsum("bchk,bkhd->bchd", pr.astype(v_all.dtype),
                               v_all,
                               preferred_element_type=jnp.float32)
                o = o.astype(qh.dtype)
                x = x + _dense(o.reshape(-1, C, dim), blk["attn_out"],
                               dtype)
                y = _layer_norm(x, blk["norm2"]["scale"],
                                blk["norm2"]["bias"])
                h = _dense(y, blk["mlp_up"], dtype)
                h = jax.nn.gelu(h)
                x = x + _dense(h, blk["mlp_down"], dtype)
            xf = _layer_norm(x, p["final_norm"]["scale"],
                             p["final_norm"]["bias"])
            logits = jnp.einsum("bcd,vd->bcv", xf.astype(jnp.float32),
                                table.astype(jnp.float32))
            return arena_k, arena_v, scale_k, scale_v, logits

        if quant:
            def verify(params, ak, av, sk, sv, tokens, positions, tables,
                       n_valid):
                return body(params, ak, av, sk, sv, tokens, positions,
                            tables, n_valid)
        else:
            def verify(params, ak, av, tokens, positions, tables,
                       n_valid):
                ak, av, _sk, _sv, out = body(params, ak, av, None, None,
                                             tokens, positions, tables,
                                             n_valid)
                return ak, av, out

        arenas, donate = self._arena_abstract()
        jitted = jax.jit(verify, donate_argnums=donate)  # lint: allow-compile
        abstract = arenas + (
            jax.ShapeDtypeStruct((batch, C), np.int32),
            jax.ShapeDtypeStruct((batch, C), np.int32),
            jax.ShapeDtypeStruct((batch, W), np.int32),
            jax.ShapeDtypeStruct((batch,), np.int32),
        )
        return jitted, abstract

    # -- copy-on-write block copy -----------------------------------------
    def _cow_spec(self):
        """Device block copy ``src -> dst`` across every layer (and the
        scale planes when int8) — the copy-on-write a full-prefix-hit
        joiner owes before it may write its final prompt block."""
        import jax
        quant = self.kv.quantized

        if quant:
            def cow(params, ak, av, sk, sv, src, dst):
                ak = ak.at[:, dst].set(ak[:, src])
                av = av.at[:, dst].set(av[:, src])
                sk = sk.at[:, dst].set(sk[:, src])
                sv = sv.at[:, dst].set(sv[:, src])
                return ak, av, sk, sv
        else:
            def cow(params, ak, av, src, dst):
                ak = ak.at[:, dst].set(ak[:, src])
                av = av.at[:, dst].set(av[:, src])
                return ak, av

        arenas, donate = self._arena_abstract()
        jitted = jax.jit(cow, donate_argnums=donate)  # lint: allow-compile
        abstract = arenas + (
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
        )
        return jitted, abstract

    def release(self) -> None:
        """Drop programs + arena accounting (lane shutdown)."""
        self._programs.clear()
        self.entry.kv_arena_bytes = 0


# ---------------------------------------------------------------------------
# the lane executor


class GenerateLane:
    """Single-threaded decode executor for one generative model.

    Owns the arena and the active set; caller threads only touch the
    admission queue and the (thread-safe) block ledger. ``start=False``
    leaves the thread unstarted so tests drive :meth:`step` directly
    under an injected clock.
    """

    def __init__(self, server, model: str, *, clock=None,
                 start: bool = True):
        self.server = server
        self.model = model
        self.clock = clock if clock is not None else server.clock
        entry = server.registry.get(model)
        self.gen = GenerativeEntry(entry)
        server.registry.touch(entry)
        # speculative decoding: the draft model gets its OWN entry (its
        # own arena + programs) sized to the same sequence envelope, so
        # target and draft block ledgers never interact
        self.draft: Optional[GenerativeEntry] = None
        draft_name = str(mmlconfig.get("generate.draft_model")).strip()
        if draft_name and self.gen.spec_tokens > 0:
            dentry = server.registry.get(draft_name)
            self.draft = GenerativeEntry(
                dentry, max_seq_len=self.gen.max_seq_len,
                max_sequences=self.gen.max_sequences)
            if self.draft.vocab != self.gen.vocab:
                raise ValueError(
                    f"draft model {draft_name!r} vocab {self.draft.vocab} "
                    f"!= target {model!r} vocab {self.gen.vocab}")
            server.registry.touch(dentry)
        self.batcher = ContinuousBatcher(self.gen.max_sequences,
                                         clock=self.clock)
        self._prefilling: List[_Seq] = []   # joined the arena, mid-chunk
        # deliberately unbounded: backpressure is the KV arena — submit()
        # reserved every enqueued sequence's full block budget, so the
        # queue can never hold more than the arena admits
        self._queue: "queue.Queue" = queue.Queue(maxsize=0)
        self._lock = threading.Lock()
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._seq_ids = 0
        self._admitted = server._twin("generate.admitted")
        self._shed = server._twin("generate.shed")
        self._expired = server._twin("generate.expired")
        self._completed = server._twin("generate.completed")
        self._failed = server._twin("generate.failed")
        self._prefix_hits = server._twin("generate.prefix_hits")
        self._prefix_misses = server._twin("generate.prefix_misses")
        self._cow_copies = server._twin("generate.cow_copies")
        self._spec_proposed = server._twin("generate.spec_proposed")
        self._spec_accepted = server._twin("generate.spec_accepted")
        self._draft_prefix_hits = server._twin("generate.draft_prefix_hits")
        self.steps = 0          # decode steps taken (chaos kill trigger)
        if events.recording_enabled():
            kv = self.gen.kv
            events.emit("decode", "arena", model=self.model,
                        blocks=kv.num_blocks,
                        block_tokens=kv.block_tokens,
                        kv_dtype=str(kv.dtype),
                        arena_bytes=kv.arena_bytes(),
                        unquantized_bytes=kv.unquantized_arena_bytes())
        if start:
            self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"mmlspark-tpu-generate-{self.model}",
            daemon=True)
        self._thread.start()

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Stop the executor and fail everything unfinished with
        :class:`ServerClosed` — generation state dies with the replica,
        and the fleet router maps a closed replica to a failover that
        RESTARTS the sequence from its prompt on a survivor (seeded
        sampling replays the identical tokens). Idempotent."""
        from mmlspark_tpu.serve.server import ServerClosed
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if timeout_s is None:
            timeout_s = float(mmlconfig.get("serving.drain_timeout_s"))
        if self._thread is not None:
            self._queue.put(_STOP)
            self._thread.join(timeout=max(timeout_s, 0.1))
            self._thread = None
        leftovers = [s for s in self._drain_queue() if s is not _STOP]
        leftovers.extend(self.batcher.drain())
        leftovers.extend(self._prefilling)
        self._prefilling.clear()
        for seq in leftovers:
            self._release_blocks(seq)
            if not seq.future.done():
                self._failed.inc()
                seq.future.set_exception(ServerClosed(
                    "server closed mid-generation; restart from prompt "
                    "elsewhere"))
        self.gen.release()
        if self.draft is not None:
            self.draft.release()

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission (caller threads) ---------------------------------------
    def submit(self, req: GenerateRequest) -> Future:
        from mmlspark_tpu.serve.server import (
            ServerClosed, ServerOverloaded, _mint_trace_id,
        )
        if self._closed:
            raise ServerClosed("generate lane closed")
        prompt = np.asarray(req.prompt, np.int32).ravel()
        if prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if prompt.size >= self.gen.max_seq_len:
            raise ValueError(
                f"prompt of {prompt.size} tokens leaves no room under "
                f"generate.max_seq_len={self.gen.max_seq_len}")
        max_new = min(int(req.max_new_tokens),
                      self.gen.max_seq_len - int(prompt.size))
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {req.max_new_tokens}")
        req = GenerateRequest(
            model=req.model, prompt=prompt, max_new_tokens=max_new,
            temperature=req.temperature, top_k=req.top_k, seed=req.seed,
            eos_id=req.eos_id, deadline_ms=req.deadline_ms,
            trace_id=req.trace_id or _mint_trace_id())
        now = self.clock()
        deadline = now + req.deadline_ms / 1e3 if req.deadline_ms else None
        with self._lock:
            if self._closed:
                raise ServerClosed("generate lane closed")
            self._seq_ids += 1
            seq_id = f"{self.model}/s{self._seq_ids}"
        # the whole lifetime's blocks up front: the prefill bucket's span
        # now, the generated tail later — admission is the ONLY place a
        # sequence can fail for memory
        bucket = bucket_for(prompt.size, self.gen.prefill_buckets)
        span_tokens = max(bucket, prompt.size + max_new)
        hashes: List[str] = []
        if self.gen.prefix_cache:
            hashes = prefix_block_hashes(
                self.model, self.gen.kv.dtype.name, prompt,
                self.gen.block_tokens)
        fault_site("generate.enqueue", {"model": self.model,
                                        "prompt": int(prompt.size)})
        blocks = self.gen.kv.try_reserve(
            seq_id, span_tokens, prefix_hashes=hashes,
            prompt_tokens=int(prompt.size))
        if blocks is None:
            self._shed.inc()
            if events.recording_enabled():
                events.emit("generate", "shed", model=self.model,
                            prompt=int(prompt.size), tokens=span_tokens,
                            free_blocks=self.gen.kv.free_blocks,
                            trace_id=req.trace_id)
            raise ServerOverloaded(
                f"KV arena full ({self.gen.kv.free_blocks} free blocks < "
                f"{blocks_needed(span_tokens, self.gen.block_tokens)} "
                "needed); retry with backoff",
                retry_after=float(mmlconfig.get("serving.retry_after_s")))
        seq = _Seq(seq_id, req, Future(), now, deadline)
        seq.future.trace_id = req.trace_id
        seq.hashes = hashes
        info = self.gen.kv.reserve_info(seq_id)
        seq.prefix_hits = int(info["hits"])
        if info["hits"]:
            self._prefix_hits.inc(info["hits"])
        if info["misses"]:
            self._prefix_misses.inc(info["misses"])
        if self.draft is not None:
            # best-effort: a full draft arena only disables speculation
            # for this sequence, it never sheds the request. The draft
            # reservation goes through the SAME prefix-matching admission
            # as the target's, keyed by the draft's own (name, dtype) —
            # a repeated prompt skips the draft prefill compute too.
            dhashes: List[str] = []
            if self.draft.prefix_cache:
                dhashes = prefix_block_hashes(
                    self.draft.entry.name, self.draft.kv.dtype.name,
                    prompt, self.draft.block_tokens)
            seq.spec_ok = self.draft.kv.try_reserve(
                seq_id, span_tokens, prefix_hashes=dhashes,
                prompt_tokens=int(prompt.size)) is not None
            if seq.spec_ok:
                seq.draft_hashes = dhashes
                dhits = int(self.draft.kv.reserve_info(seq_id)["hits"])
                if dhits:
                    self._draft_prefix_hits.inc(dhits)
        if hashes and events.recording_enabled():
            events.emit("decode", "prefix", model=self.model,
                        hits=int(info["hits"]), misses=int(info["misses"]),
                        cached_tokens=int(info["cached_tokens"]),
                        cow=bool(info["pending_cow"]),
                        trace_id=req.trace_id)
        self._queue.put(seq)
        self._admitted.inc()
        return seq.future

    # -- executor ----------------------------------------------------------
    def _run(self) -> None:
        hb = _watchdog.register(f"generate.{self.model}")
        try:
            self._run_loop(hb)
        finally:
            hb.close()

    def _run_loop(self, hb) -> None:
        stopping = False
        while True:
            hb.beat()
            busy = self.batcher.ready() or bool(self._prefilling)
            try:
                item = self._queue.get(timeout=0.0 if busy else 0.05)
            except queue.Empty:
                item = None
            if item is _STOP:
                stopping = True
            elif item is not None:
                self.batcher.offer(item)
            for s in self._drain_queue():
                if s is _STOP:
                    stopping = True
                else:
                    self.batcher.offer(s)
            if stopping:
                return              # close() resolves whatever is left
            if self.batcher.ready() or self._prefilling:
                self.step()

    def _drain_queue(self) -> List:
        out: List = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                return out

    # -- one continuous-batching step (public: tests drive it) ------------
    def step(self) -> None:
        """Advance mid-prefill sequences one chunk, admit joiners
        (prefill + first token), then run ONE decode step over the active
        set — chunked prefill interleaves with decode at exactly this
        boundary, so a long joiner costs the running batch one chunk of
        latency per step instead of its whole prompt. Sequences finishing
        this step leave and free their blocks before the next step's
        joiners are considered."""
        for s in self._drain_queue():
            if s is not _STOP:
                self.batcher.offer(s)
        for seq in list(self._prefilling):
            self._prefill_chunk_step(seq)
        taken = self.batcher.take()
        room = max(0, self.batcher.free_slots - len(self._prefilling))
        for seq in reversed(taken[room:]):
            self.batcher.requeue(seq)   # slots held by mid-chunk prefills
        for seq in taken[:room]:
            self._admit_one(seq)
        if self.batcher.active:
            if self.draft is not None:
                self._decode_step_spec()
            else:
                self._decode_step()
        if metrics.metrics_enabled():
            metrics.gauge("generate.kv_occupancy").set(
                self.gen.kv.occupancy())

    def _admit_one(self, seq: _Seq) -> None:
        now = self.clock()
        if seq.expired(now):
            from mmlspark_tpu.serve.server import RequestExpired
            self._release_blocks(seq)
            self._expired.inc()
            if events.recording_enabled():
                events.emit("generate", "expired", model=self.model,
                            trace_id=seq.trace_id,
                            waited_ms=round((now - seq.enqueued) * 1e3, 3))
            seq.future.set_exception(RequestExpired(
                "deadline passed before prefill"))
            return
        gen = self.gen
        Lp = int(seq.prompt.size)
        info = gen.kv.reserve_info(seq.seq_id)
        cached = min(int(info["cached_tokens"]), Lp)
        cow = gen.kv.take_pending_cow(seq.seq_id)
        if cow is not None:
            # full-prefix hit: copy the final shared block into this
            # sequence's owned block BEFORE its first (re)write
            try:
                self._cow_copy(gen, cow)
            except Exception as e:
                logger.error("cow copy failed for %s: %s", seq.seq_id, e)
                self._fail_seq(seq, e)
                return
            gen.kv.cow_done(seq.seq_id)
            self._cow_copies.inc()
            if events.recording_enabled():
                events.emit("decode", "cow", model=self.model,
                            src=cow[0], dst=cow[1], trace_id=seq.trace_id)
        # the legacy whole-prompt prefill scatters EVERY leading block,
        # so any reservation that shares cached blocks must take the
        # chunk path (it only writes from the first uncached position)
        use_chunk = cached > 0 or (gen.prefill_chunk > 0
                                   and Lp > gen.chunk_width)
        if not use_chunk:
            try:
                self._prefill(seq)
            except Exception as e:
                logger.error("prefill failed for %s: %s", seq.seq_id, e)
                self._fail_seq(seq, e)
                return
            self.batcher.join(seq)
            if seq.finish:          # eos / budget hit on the first token
                self._finish(seq)
            return
        # chunk path: compute only the uncached suffix, one chunk per
        # lane step; a FULL hit recomputes just the last prompt position
        # (into the CoW'd block) to sample its first token
        seq.prefill_pos = cached if cached < Lp else max(Lp - 1, 0)
        self._prefilling.append(seq)
        self._prefill_chunk_step(seq)

    def _prefill(self, seq: _Seq) -> None:
        gen = self.gen
        Lp = int(seq.prompt.size)
        bucket = bucket_for(Lp, gen.prefill_buckets)
        nb = bucket // gen.block_tokens
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :Lp] = seq.prompt
        block_ids = np.asarray(gen.kv.blocks_for(seq.seq_id)[:nb], np.int32)
        program = gen.program_for("prefill", bucket)
        fault_site("generate.prefill", {"model": self.model,
                                        "bucket": bucket})
        t0 = self.clock()
        with spans.span("decode", "prefill", model=self.model,
                        bucket=bucket):
            row = self._call(gen, program, tokens, np.int32(Lp - 1),
                             block_ids)
            logits = np.asarray(
                syncs.device_get(row, "generate.prefill"), np.float32)
        if seq.hashes:
            gen.kv.register_prefix(seq.seq_id, seq.hashes)
        self._draft_prefill(seq)
        now = self.clock()
        self._append_token(seq, logits, position=Lp)
        seq.ttft_s = now - seq.enqueued
        seq.last_t = now
        if metrics.metrics_enabled():
            metrics.histogram("generate.ttft_ms").observe(
                seq.ttft_s * 1e3, exemplar=seq.trace_id)
        if events.recording_enabled():
            events.emit("decode", "prefill", model=self.model,
                        bucket=bucket, prompt=Lp,
                        prefill_ms=round((now - t0) * 1e3, 3),
                        trace_id=seq.trace_id)

    # -- shared program-call plumbing --------------------------------------
    @staticmethod
    def _call(entry: GenerativeEntry, program, *operands):
        """Run one arena program against ``entry``'s KV manager: pass the
        current (donated) arena set, store the returned set back, and
        hand the caller whatever payload follows it (logits/row), if
        any. Works for the target and the draft entry alike."""
        kv = entry.kv
        if kv.quantized:
            out = program(entry.params, kv.arena_k, kv.arena_v,
                          kv.scale_k, kv.scale_v, *operands)
            kv.swap(*out[:4])
            tail = out[4:]
        else:
            out = program(entry.params, kv.arena_k, kv.arena_v, *operands)
            kv.swap(*out[:2])
            tail = out[2:]
        return tail[0] if tail else None

    def _cow_copy(self, entry: GenerativeEntry,
                  pair: Tuple[int, int]) -> None:
        program = entry.program_for("cow", 0)
        self._call(entry, program, np.int32(pair[0]), np.int32(pair[1]))

    def _release_blocks(self, seq: _Seq) -> None:
        """Free every block lease the sequence holds — target arena and,
        when speculation reserved one, the draft arena (both idempotent)."""
        self.gen.kv.free(seq.seq_id)
        if self.draft is not None:
            self.draft.kv.free(seq.seq_id)

    def _fail_seq(self, seq: _Seq, exc: Exception) -> None:
        self._release_blocks(seq)
        self._failed.inc()
        if not seq.future.done():
            seq.future.set_exception(exc)

    # -- chunked / suffix prefill ------------------------------------------
    def _prefill_chunk_step(self, seq: _Seq) -> None:
        """One chunk of ``seq``'s remaining prompt through the chunk
        program. On the final chunk the sequence samples its first token
        (TTFT), registers its prefix blocks, and joins the active set."""
        gen = self.gen
        Lp = int(seq.prompt.size)
        C = gen.chunk_width
        start = seq.prefill_pos
        n_valid = min(C, Lp - start)
        final = start + n_valid >= Lp
        tokens = np.zeros((C,), np.int32)
        tokens[:n_valid] = seq.prompt[start:start + n_valid]
        positions = (start + np.arange(C)).astype(np.int32)
        table_row = gen.kv.block_table(seq.seq_id, gen.table_width)
        program = gen.program_for("chunk", C)
        fault_site("generate.prefill", {"model": self.model, "bucket": C,
                                        "start": start})
        t0 = self.clock()
        try:
            with spans.span("decode", "prefill_chunk", model=self.model,
                            chunk=C, start=start):
                row = self._call(gen, program, tokens, positions,
                                 table_row, np.int32(n_valid))
                if final:
                    logits = np.asarray(
                        syncs.device_get(row, "generate.prefill"),
                        np.float32)
        except Exception as e:
            logger.error("chunk prefill failed for %s: %s", seq.seq_id, e)
            if seq in self._prefilling:
                self._prefilling.remove(seq)
            self._fail_seq(seq, e)
            return
        seq.prefill_pos = start + n_valid
        if not final:
            return
        self._prefilling.remove(seq)
        if seq.hashes:
            gen.kv.register_prefix(seq.seq_id, seq.hashes)
        self._draft_prefill(seq)
        now = self.clock()
        self._append_token(seq, logits, position=Lp)
        seq.ttft_s = now - seq.enqueued
        seq.last_t = now
        if metrics.metrics_enabled():
            metrics.histogram("generate.ttft_ms").observe(
                seq.ttft_s * 1e3, exemplar=seq.trace_id)
        if events.recording_enabled():
            events.emit("decode", "prefill", model=self.model,
                        bucket=C, prompt=Lp, chunked=True,
                        cached_tokens=seq.prefix_hits * gen.block_tokens,
                        prefill_ms=round((now - t0) * 1e3, 3),
                        trace_id=seq.trace_id)
        self.batcher.join(seq)
        if seq.finish:              # eos / budget hit on the first token
            self._finish(seq)

    # -- speculative decoding ----------------------------------------------
    def _draft_prefill(self, seq: _Seq) -> None:
        """Materialize the draft model's KV for the prompt. Failure only
        degrades the sequence to non-speculative decode.

        Mirrors the target's prefix-reuse admission: cached leading
        blocks (shared via the draft ledger's prefix chain) are NOT
        recomputed — only the uncached suffix runs, through the draft's
        chunk program, and a pending copy-on-write resolves before the
        first write, exactly like :meth:`_admit_one` does for the
        target. The legacy whole-prompt prefill scatters EVERY leading
        block, so any reservation with cached blocks must take the
        suffix path."""
        if self.draft is None or not seq.spec_ok:
            return
        d = self.draft
        try:
            Lp = int(seq.prompt.size)
            info = d.kv.reserve_info(seq.seq_id)
            cached = min(int(info["cached_tokens"]), Lp)
            cow = d.kv.take_pending_cow(seq.seq_id)
            if cow is not None:
                self._cow_copy(d, cow)
                d.kv.cow_done(seq.seq_id)
            if cached > 0:
                # suffix-only: recompute from the first uncached
                # position (a FULL hit redoes just the last one)
                C = d.chunk_width
                start = min(cached, Lp - 1)
                while start < Lp:
                    n_valid = min(C, Lp - start)
                    tokens = np.zeros((C,), np.int32)
                    tokens[:n_valid] = seq.prompt[start:start + n_valid]
                    positions = (start + np.arange(C)).astype(np.int32)
                    table_row = d.kv.block_table(seq.seq_id, d.table_width)
                    self._call(d, d.program_for("chunk", C), tokens,
                               positions, table_row, np.int32(n_valid))
                    start += n_valid
            else:
                bucket = bucket_for(Lp, d.prefill_buckets)
                nb = bucket // d.block_tokens
                tokens = np.zeros((1, bucket), np.int32)
                tokens[0, :Lp] = seq.prompt
                block_ids = np.asarray(d.kv.blocks_for(seq.seq_id)[:nb],
                                       np.int32)
                program = d.program_for("prefill", bucket)
                self._call(d, program, tokens, np.int32(Lp - 1), block_ids)
            if seq.draft_hashes:
                d.kv.register_prefix(seq.seq_id, seq.draft_hashes)
        except Exception as e:
            logger.warning("draft prefill failed for %s (speculation off "
                           "for this sequence): %s", seq.seq_id, e)
            d.kv.free(seq.seq_id)
            seq.spec_ok = False

    def _draft_propose(self, active: List[_Seq], fed: np.ndarray,
                       drafts: np.ndarray) -> None:
        """Run the draft model ``max(fed) - 1`` single-token decode steps
        over the spec-riding lanes, sampling each proposal with the SAME
        per-(seed, position) sampler the target uses — so a correct draft
        matches the target's token exactly, in greedy AND seeded-sampling
        modes. Lanes whose window is exhausted mask out (reserved-block
        writes), like empty decode lanes."""
        d = self.draft
        B = bucket_for(len(active), d.decode_buckets)
        W = d.table_width
        prev = np.array([seq.generated[-1] for seq in active], np.int64)
        for j in range(drafts.shape[1]):
            tokens = np.zeros((B,), np.int32)
            positions = np.zeros((B,), np.int32)
            tables = np.full((B, W), RESERVED_BLOCK, np.int32)
            seq_lens = np.zeros((B,), np.int32)
            lanes = [i for i, seq in enumerate(active)
                     if j < int(fed[i]) - 1]
            if not lanes:
                return
            for i in lanes:
                seq = active[i]
                tokens[i] = prev[i]
                positions[i] = seq.seq_len - 1 + j
                tables[i] = d.kv.block_table(seq.seq_id, W)
                seq_lens[i] = seq.seq_len + j
            program = d.program_for("decode", B)
            logits = self._call(d, program, tokens, positions, tables,
                                seq_lens)
            rows = np.asarray(
                syncs.device_get(logits, "generate.draft"), np.float32)
            for i in lanes:
                seq = active[i]
                tok = sample_token(rows[i], temperature=seq.temperature,
                                   top_k=seq.top_k, seed=seq.seed,
                                   position=seq.seq_len + j)
                drafts[i, j] = tok
                prev[i] = tok

    def _decode_step_spec(self) -> None:
        """One speculative step: the draft proposes up to ``spec_tokens``
        tokens per lane, the target checks the whole window in ONE verify
        call, and each lane accepts proposals left to right while they
        match what the target's own sampler would have emitted — so the
        output stream is token-identical to plain decode, at up to
        ``spec_width`` tokens per target step. Lanes that cannot
        speculate (draft arena full, window exhausted) ride the same
        program with a one-token window."""
        gen = self.gen
        active = self.batcher.active
        B = bucket_for(len(active), gen.decode_buckets)
        C = gen.spec_width
        W = gen.table_width
        fed = np.ones((len(active),), np.int64)
        for i, seq in enumerate(active):
            remaining = seq.max_new - len(seq.generated)
            if seq.spec_ok and remaining > 1:
                fed[i] = min(C, remaining)
        gamma = int(fed.max()) - 1
        drafts = np.zeros((len(active), max(gamma, 0)), np.int64)
        if gamma > 0:
            self._draft_propose(active, fed, drafts)
        tokens = np.zeros((B, C), np.int32)
        positions = np.zeros((B, C), np.int32)
        tables = np.full((B, W), RESERVED_BLOCK, np.int32)
        n_valid = np.zeros((B,), np.int32)
        for i, seq in enumerate(active):
            f = int(fed[i])
            tokens[i, 0] = seq.generated[-1]
            tokens[i, 1:f] = drafts[i, :f - 1]
            positions[i] = seq.seq_len - 1 + np.arange(C)
            tables[i] = gen.kv.block_table(seq.seq_id, W)
            n_valid[i] = f
        program = gen.program_for("verify", B)
        fault_site("generate.step", {"model": self.model, "batch": B,
                                     "active": len(active)})
        t0 = self.clock()
        with spans.span("decode", "step", model=self.model, batch=B,
                        active=len(active), spec=True):
            logits = self._call(gen, program, tokens, positions, tables,
                                n_valid)
            rows = np.asarray(
                syncs.device_get(logits, "generate.step"), np.float32)
        now = self.clock()
        self.steps += 1
        hot = metrics.metrics_enabled()
        emitted = 0
        for i, seq in enumerate(active):
            f = int(fed[i])
            appended = 0
            matched = 0
            for j in range(f):
                self._append_token(seq, rows[i, j], position=seq.seq_len)
                appended += 1
                if seq.finish:
                    break
                if j < f - 1:
                    if seq.generated[-1] != int(drafts[i, j]):
                        break       # divergence: the window past j is junk
                    matched += 1
            if f > 1:
                seq.spec_proposed += f - 1
                seq.spec_accepted += matched
                self._spec_proposed.inc(f - 1)
                self._spec_accepted.inc(matched)
            emitted += appended
            gap = (now - seq.last_t) / appended
            seq.last_t = now
            seq.itl_s.extend([gap] * appended)
            if hot:
                metrics.histogram("generate.itl_ms").observe(
                    gap * 1e3, exemplar=seq.trace_id)
            if not seq.finish and seq.expired(now):
                seq.finish = "deadline"
            if seq.finish:
                self._finish(seq)
        if events.recording_enabled():
            events.emit("decode", "step", model=self.model, batch=B,
                        active=len(active), tokens=emitted, spec=True,
                        step_ms=round((now - t0) * 1e3, 3))

    def _decode_step(self) -> None:
        gen = self.gen
        active = self.batcher.active
        bucket = bucket_for(len(active), gen.decode_buckets)
        W = gen.table_width
        tokens = np.zeros((bucket,), np.int32)
        positions = np.zeros((bucket,), np.int32)
        tables = np.full((bucket, W), RESERVED_BLOCK, np.int32)
        seq_lens = np.zeros((bucket,), np.int32)
        for i, seq in enumerate(active):
            tokens[i] = seq.generated[-1]
            positions[i] = seq.seq_len - 1      # the fed token's position
            tables[i] = gen.kv.block_table(seq.seq_id, W)
            seq_lens[i] = seq.seq_len
        program = gen.program_for("decode", bucket)
        fault_site("generate.step", {"model": self.model, "batch": bucket,
                                     "active": len(active)})
        t0 = self.clock()
        with spans.span("decode", "step", model=self.model, batch=bucket,
                        active=len(active)):
            logits = self._call(gen, program, tokens, positions, tables,
                                seq_lens)
            rows = np.asarray(
                syncs.device_get(logits, "generate.step"), np.float32)
        now = self.clock()
        self.steps += 1
        hot = metrics.metrics_enabled()
        for i, seq in enumerate(active):
            self._append_token(seq, rows[i], position=seq.seq_len)
            gap = now - seq.last_t
            seq.last_t = now
            seq.itl_s.append(gap)
            if hot:
                metrics.histogram("generate.itl_ms").observe(
                    gap * 1e3, exemplar=seq.trace_id)
            if not seq.finish and seq.expired(now):
                seq.finish = "deadline"     # partial result, not an error
            if seq.finish:
                self._finish(seq)
        if events.recording_enabled():
            events.emit("decode", "step", model=self.model, batch=bucket,
                        active=len(active),
                        step_ms=round((now - t0) * 1e3, 3))

    def _append_token(self, seq: _Seq, logits: np.ndarray,
                      position: int) -> None:
        tok = sample_token(logits, temperature=seq.temperature,
                           top_k=seq.top_k, seed=seq.seed,
                           position=position)
        seq.generated.append(tok)
        if seq.eos_id is not None and tok == seq.eos_id:
            seq.finish = "stop"
        elif len(seq.generated) >= seq.max_new:
            seq.finish = seq.finish or "length"

    def _finish(self, seq: _Seq) -> None:
        self.batcher.leave(seq)
        freed = self.gen.kv.free(seq.seq_id)
        if self.draft is not None:
            self.draft.kv.free(seq.seq_id)
        self._completed.inc()
        now = self.clock()
        if events.recording_enabled():
            itl = seq.itl_s
            events.emit("generate", "request", model=self.model,
                        prompt=int(seq.prompt.size),
                        tokens=len(seq.generated), finish=seq.finish,
                        ttft_ms=round((seq.ttft_s or 0.0) * 1e3, 3),
                        itl_mean_ms=round(sum(itl) / len(itl) * 1e3, 3)
                        if itl else 0.0,
                        itl_max_ms=round(max(itl) * 1e3, 3) if itl
                        else 0.0,
                        total_ms=round((now - seq.enqueued) * 1e3, 3),
                        kv_occupancy=round(self.gen.kv.occupancy(), 4),
                        prefix_hits=seq.prefix_hits,
                        spec_proposed=seq.spec_proposed,
                        spec_accepted=seq.spec_accepted,
                        trace_id=seq.trace_id)
            events.emit("decode", "evict", model=self.model,
                        blocks=freed, trace_id=seq.trace_id)
        seq.future.set_result(seq.result())

    # -- introspection -----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        s = {"admitted": self._admitted.value,
             "shed": self._shed.value,
             "expired": self._expired.value,
             "completed": self._completed.value,
             "failed": self._failed.value,
             "waiting": len(self.batcher),
             "active": len(self.batcher.active),
             "prefilling": len(self._prefilling),
             "prefix_hits": self._prefix_hits.value,
             "prefix_misses": self._prefix_misses.value,
             "cow_copies": self._cow_copies.value,
             "spec_proposed": self._spec_proposed.value,
             "spec_accepted": self._spec_accepted.value,
             "steps": self.steps}
        s.update({f"kv.{k}": v for k, v in self.gen.kv.stats().items()})
        if self.draft is not None:
            s["draft.kv.used_blocks"] = self.draft.kv.used_blocks
            s["draft.kv.free_blocks"] = self.draft.kv.free_blocks
            s["draft_prefix_hits"] = self._draft_prefix_hits.value
        return s
