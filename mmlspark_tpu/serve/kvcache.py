"""Paged KV-cache arena for the generative serving lane.

Autoregressive decode is memory-bound on the key/value history: a naive
per-sequence ``(max_seq_len, heads, head_dim)`` allocation wastes HBM on
short sequences and fragments it as sequences of different lengths join
and leave the in-flight batch. This module is the vLLM-style answer
(PAPERS.md: PagedAttention) sized for this framework: ONE fixed arena of
``num_blocks`` fixed-size blocks per layer, allocated once at lane
warm-up, with a host-side block ledger handing ``ceil(len /
block_tokens)`` blocks to each admitted sequence and reclaiming them the
step the sequence finishes.

Contracts the rest of the lane builds on:

- **Fixed footprint.** The arena never grows. Admission that cannot get
  its blocks is SHED (the server raises a retryable ``ServerOverloaded``)
  — decode never OOMs mid-sequence, because a sequence's full block
  budget (prompt + ``max_new_tokens``) is reserved up front.
- **Block 0 is reserved scratch.** Decode programs run at a fixed batch
  bucket; lanes without a live sequence route their (masked, garbage)
  writes to block 0 so the compiled program never branches on occupancy.
  Real sequences are handed blocks ``1..num_blocks-1`` only.
- **Shared-prefix reuse (refcounted blocks).** Full prompt blocks are
  content-addressed: the lane registers each under a CHAINED hash
  (``sha256(prev_hash | token block)``, so identical tokens after
  different prefixes never collide) and a later reservation carrying the
  same hash chain shares the block instead of re-prefilling it. Blocks
  therefore carry a refcount; a block is only writable by a sequence
  when its refcount is 1 (copy-on-write otherwise — see
  :meth:`KVCacheManager.prepare_write`), and a freed block that still
  holds indexed prefix content parks in an LRU cached pool rather than
  the free list, reclaimed (refcount 0 only) when admission needs room.
- **Donation round-trip.** The decode/prefill executables donate the
  arena buffers (in-place update on TPU); callers pass
  ``arena_k``/``arena_v`` (and the quantization scales, when int8) in
  and MUST store the returned set back via :meth:`swap` before the next
  step.
- **int8 storage (optional).** ``generate.kv_dtype=int8`` stores the
  arena quantized with one fp32 scale per (layer, block, row): roughly
  2x the concurrent-sequence capacity at the same byte budget.
  :func:`quantize_rows` / :func:`dequantize_rows` are the ONLY
  quantization arithmetic in ``serve/`` (lint Rule 13) — program
  builders call them, they never open-code scale math.
- **Budget accounting.** ``arena_bytes()`` (arena + scales, real width)
  is charged to the owning :class:`~mmlspark_tpu.serve.registry.ModelEntry`
  so the registry's ``runtime.device_cache_mb`` LRU sees scoring params
  and decode arena as one HBM tenant set (``generate.arena_mb`` sizes
  the arena itself; 0 derives it from ``generate.max_sequences`` x
  ``generate.max_seq_len``).

This module is the ONE sanctioned device-allocation site in ``serve/``
(lint Rule 10): everything else goes through the registry or marks an
explicit ``# lint: allow-alloc``.
"""
from __future__ import annotations

import hashlib
import math
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.observability import memory as devmem
from mmlspark_tpu.observability import metrics
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.kvcache")

RESERVED_BLOCK = 0  # scratch target for masked decode lanes; never leased


def blocks_needed(tokens: int, block_tokens: int) -> int:
    """Blocks covering ``tokens`` positions at the arena granule."""
    return max(1, math.ceil(int(tokens) / int(block_tokens)))


def prefix_block_hashes(model: str, kv_dtype: str, prompt: Sequence[int],
                        block_tokens: int) -> List[str]:
    """Chained content hashes for every FULL block of ``prompt``.

    ``h[i] = sha256(h[i-1] | tokens of block i)`` — the chain makes a
    block's identity a function of the ENTIRE prefix through it, which is
    what its cached K/V actually depends on. The partial trailing block
    (if any) is never hashed: its K/V would be extended in place by
    decode, so it is never shareable.
    """
    toks = np.asarray(prompt, np.int32).ravel()
    out: List[str] = []
    prev = f"{model}|{kv_dtype}|bt={int(block_tokens)}".encode()
    for i in range(int(toks.size) // int(block_tokens)):
        h = hashlib.sha256()
        h.update(prev)
        h.update(toks[i * block_tokens:(i + 1) * block_tokens].tobytes())
        prev = h.digest()
        out.append(h.hexdigest())
    return out


# ---------------------------------------------------------------------------
# int8 block quantization — the ONE quant-arithmetic site in serve/
# (lint Rule 13). Traced inside the compiled prefill/decode/verify
# programs; per-row scales keep incremental single-position writes exact
# (a whole-block scale would invalidate already-written rows).


def quantize_rows(x):
    """``(..., heads, head_dim)`` float rows -> (int8 rows, fp32 scales
    shaped ``(...,)``). Symmetric per-row absmax scaling to [-127, 127]."""
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = jnp.maximum(amax / 127.0, 1e-12)
    q = jnp.clip(jnp.round(xf / scale[..., None, None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_rows(q, scale):
    """Invert :func:`quantize_rows`: int8 rows + per-row scales -> fp32."""
    import jax.numpy as jnp
    return q.astype(jnp.float32) * scale[..., None, None].astype(jnp.float32)


class KVCacheManager:
    """Fixed paged KV arena + host-side block ledger (thread-safe).

    The device arrays are buffers shaped
    ``(layers, num_blocks, block_tokens, heads, head_dim)`` (plus
    ``(layers, num_blocks, block_tokens)`` fp32 scales when quantized) —
    single-device by default, or with the HEAD axis sharded over the
    ``tensor`` mesh axis when a model mesh is passed (big-model decode);
    the ledger (free list, refcounts, prefix index, per-sequence leases)
    lives entirely on the host, is shard-agnostic, and never touches the
    device on reserve/free either way.

    Block lifecycle::

        free -> leased (refcount 1..N, shared via the prefix index)
             -> cached (refcount 0, content still indexed; LRU)
             -> free  (evicted under admission pressure, or de-indexed)
    """

    def __init__(self, *, layers: int, heads: int, head_dim: int,
                 num_blocks: int, block_tokens: int, dtype=np.float32,
                 kv_dtype=None, mesh=None, shard_heads: bool = True):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {RESERVED_BLOCK} is "
                f"reserved scratch), got {num_blocks}")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.compute_dtype = np.dtype(dtype)
        self.dtype = np.dtype(kv_dtype) if kv_dtype is not None \
            else self.compute_dtype
        self.quantized = self.dtype == np.dtype(np.int8)
        import jax.numpy as jnp
        shape = (self.layers, self.num_blocks, self.block_tokens,
                 self.heads, self.head_dim)
        # mesh placement: the head axis shards over `tensor` (the same
        # split the attention projections use), everything else — and the
        # whole host-side ledger below — is shard-agnostic. Zeros are
        # device_put from host so each chip only ever allocates its shard.
        self.mesh = mesh
        if mesh is not None:
            import jax
            from mmlspark_tpu.parallel.sharding import (
                kv_arena_sharding, kv_scale_sharding, replicated,
            )
            # a mesh-bound model's arena MUST live on that mesh either
            # way (mixed-placement operands don't compose in one
            # program); shard_heads=False keeps it replicated there
            self.arena_sharding = kv_arena_sharding(mesh, self.heads) \
                if shard_heads else replicated(mesh)
            self.scale_sharding = kv_scale_sharding(mesh)
            self.arena_k = jax.device_put(np.zeros(shape, self.dtype),
                                          self.arena_sharding)
            self.arena_v = jax.device_put(np.zeros(shape, self.dtype),
                                          self.arena_sharding)
        else:
            self.arena_sharding = self.scale_sharding = None
            self.arena_k = jnp.zeros(shape, self.dtype)
            self.arena_v = jnp.zeros(shape, self.dtype)
        if self.quantized:
            sshape = (self.layers, self.num_blocks, self.block_tokens)
            if mesh is not None:
                import jax
                self.scale_k = jax.device_put(np.ones(sshape, np.float32),
                                              self.scale_sharding)
                self.scale_v = jax.device_put(np.ones(sshape, np.float32),
                                              self.scale_sharding)
            else:
                self.scale_k = jnp.ones(sshape, np.float32)
                self.scale_v = jnp.ones(sshape, np.float32)
        else:
            self.scale_k = self.scale_v = None
        self._lock = threading.Lock()
        # LIFO free list: recently-freed blocks are re-leased first, which
        # keeps the hot working set compact in HBM
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._leases: Dict[str, List[int]] = {}
        # prefix-reuse ledger: refcounts for leased blocks, the content
        # index (chained hash -> block, 1:1 both ways), the LRU pool of
        # refcount-0 blocks still holding indexed content, and per-lease
        # reservation metadata (hit counts + pending copy-on-write)
        self._refcount: Dict[int, int] = {}
        self._index: Dict[str, int] = {}
        self._block_hash: Dict[int, str] = {}
        self._cached: "OrderedDict[int, str]" = OrderedDict()
        self._meta: Dict[str, Dict[str, Any]] = {}
        # per-hash chain metadata (parent link, registration depth, hit
        # count, last-use tick) — the source of the top-K resident-chain
        # summary replicas advertise for fleet prefix affinity
        self._hmeta: Dict[str, Dict[str, Any]] = {}
        self._tick = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.cow_copies = 0
        self.prefix_evictions = 0
        self._update_gauge()

    # -- sizing ------------------------------------------------------------
    @classmethod
    def from_config(cls, *, layers: int, heads: int, head_dim: int,
                    dtype=np.float32, mesh=None,
                    shard_heads: bool = True) -> "KVCacheManager":
        """Size the arena from the ``generate.*`` config namespace:
        ``generate.arena_mb`` when set, else enough blocks for
        ``generate.max_sequences`` sequences of ``generate.max_seq_len``
        tokens (plus the reserved scratch block). ``generate.kv_dtype``
        picks the storage width — at a fixed ``arena_mb``, int8 storage
        buys roughly 2x the blocks (the capacity win the decode bench
        lane reports)."""
        bt = int(mmlconfig.get("generate.kv_block_tokens"))
        arena_mb = float(mmlconfig.get("generate.arena_mb"))
        cfg_dtype = str(mmlconfig.get("generate.kv_dtype")).strip().lower()
        kv_dtype = np.dtype(cfg_dtype) if cfg_dtype else None
        if arena_mb > 0:
            storage = kv_dtype if kv_dtype is not None else np.dtype(dtype)
            per_block = devmem.nbytes_of((2, layers, bt, heads, head_dim),
                                         storage)
            if storage == np.dtype(np.int8):
                per_block += devmem.nbytes_of((2, layers, bt), np.float32)
            num_blocks = max(2, int(arena_mb * 1e6 // per_block))
        else:
            seqs = int(mmlconfig.get("generate.max_sequences"))
            max_len = int(mmlconfig.get("generate.max_seq_len"))
            num_blocks = 1 + seqs * blocks_needed(max_len, bt)
        return cls(layers=layers, heads=heads, head_dim=head_dim,
                   num_blocks=num_blocks, block_tokens=bt, dtype=dtype,
                   kv_dtype=kv_dtype, mesh=mesh, shard_heads=shard_heads)

    def arena_bytes(self) -> int:
        """Total HBM footprint of both arenas at their REAL storage width,
        plus the quantization scales when int8 (charged to the owning
        registry entry so the device-cache LRU accounts for it); the
        arithmetic itself lives in the HBM ledger (lint Rule 11)."""
        n = 2 * devmem.nbytes_of(
            (self.layers, self.num_blocks, self.block_tokens,
             self.heads, self.head_dim), self.dtype)
        if self.quantized:
            n += 2 * devmem.nbytes_of(
                (self.layers, self.num_blocks, self.block_tokens),
                np.float32)
        return n

    def unquantized_arena_bytes(self) -> int:
        """What the same block count would cost at the compute dtype —
        the denominator of the int8-savings number in reports."""
        return 2 * devmem.nbytes_of(
            (self.layers, self.num_blocks, self.block_tokens,
             self.heads, self.head_dim), self.compute_dtype)

    def arena_shard_bytes(self) -> int:
        """PER-DEVICE HBM footprint: each chip holds 1/|tensor| of the
        head axis when the arena is mesh-sharded (scales stay replicated),
        the full arena otherwise. This — not :meth:`arena_bytes` — is what
        the registry charges against ``runtime.device_cache_mb``."""
        if self.arena_sharding is None:
            return self.arena_bytes()
        n = 2 * devmem.nbytes_of(
            self.arena_sharding.shard_shape(
                (self.layers, self.num_blocks, self.block_tokens,
                 self.heads, self.head_dim)), self.dtype)
        if self.quantized:
            n += 2 * devmem.nbytes_of(
                (self.layers, self.num_blocks, self.block_tokens),
                np.float32)
        return n

    # -- ledger internals (call under self._lock) --------------------------
    def _bump(self, block: int) -> None:
        """Take a share of ``block``: out of the cached pool if parked
        there, refcount += 1."""
        self._cached.pop(block, None)
        self._refcount[block] = self._refcount.get(block, 0) + 1

    def _drop(self, block: int) -> None:
        """Release one share of ``block``; at refcount 0 it parks in the
        cached pool (content still indexed) or returns to the free list."""
        n = self._refcount.get(block, 0) - 1
        if n > 0:
            self._refcount[block] = n
            return
        self._refcount.pop(block, None)
        h = self._block_hash.get(block)
        if h is not None:
            self._cached[block] = h
            self._cached.move_to_end(block)
        else:
            self._free.append(block)

    def _deindex(self, block: int) -> None:
        h = self._block_hash.pop(block, None)
        if h is not None:
            self._index.pop(h, None)
            self._hmeta.pop(h, None)

    def _take_fresh(self) -> Optional[int]:
        """One content-free block: the free list first, then the LRU
        refcount-0 cached block (its index entry dies with it)."""
        if self._free:
            return self._free.pop()
        if self._cached:
            block, _h = self._cached.popitem(last=False)
            self._deindex(block)
            self.prefix_evictions += 1
            return block
        return None

    # -- reservation -------------------------------------------------------
    def try_reserve(self, seq_id: str, tokens: int,
                    prefix_hashes: Optional[Sequence[str]] = None,
                    prompt_tokens: Optional[int] = None
                    ) -> Optional[List[int]]:
        """Lease blocks covering ``tokens`` positions for ``seq_id``.

        With ``prefix_hashes`` (the prompt's chained full-block hashes),
        leading blocks already in the prefix index are SHARED (refcount
        bump) instead of drawn from the free list — the reservation only
        pays for the uncached suffix. When the hits cover the whole
        prompt (``prompt_tokens`` block-aligned and fully matched), the
        final matched block is scheduled for copy-on-write instead of
        shared writable: the joiner's first-token recompute writes into
        position ``prompt_tokens - 1``, and no block is ever written
        while shared (see :meth:`take_pending_cow`).

        Returns the position-ordered block ids (stable for the
        sequence's lifetime) or None when free + reclaimable-cached
        blocks cannot cover the uncached ask — the caller sheds the
        request (retryable) instead of queueing into an OOM.
        """
        n = blocks_needed(tokens, self.block_tokens)
        hashes = list(prefix_hashes or ())
        with self._lock:
            if seq_id in self._leases:
                raise ValueError(f"sequence {seq_id!r} already holds blocks")
            matched: List[int] = []
            for h in hashes:
                b = self._index.get(h)
                if b is None or len(matched) >= n:
                    break
                matched.append(b)
            m = len(matched)
            full_hit = bool(hashes) and m == len(hashes) \
                and prompt_tokens is not None \
                and m * self.block_tokens >= int(prompt_tokens)
            shared = matched[:-1] if full_hit else matched
            cow_src = matched[-1] if full_hit else None
            fresh_needed = n - len(shared)
            reclaimable = len(self._free) + sum(
                1 for b in self._cached if b not in matched)
            if reclaimable < fresh_needed:
                return None                 # nothing mutated: clean shed
            # hit heat only moves once the reservation is COMMITTED — a
            # shed mutates nothing, including the digest's hit counters
            self._tick += 1
            for h in hashes[:m]:
                hm = self._hmeta.get(h)
                if hm is not None:
                    hm["hits"] += 1
                    hm["last_use"] = self._tick
            for b in shared:
                self._bump(b)
            if cow_src is not None:
                self._bump(cow_src)         # pin the copy source
            fresh: List[int] = []
            for _ in range(fresh_needed):
                b = self._take_fresh()
                assert b is not None        # guaranteed by the count above
                self._refcount[b] = 1
                fresh.append(b)
            blocks = list(shared) + fresh
            self._leases[seq_id] = blocks
            self._meta[seq_id] = {
                "hits": m,
                "misses": max(0, len(hashes) - m),
                "cached_tokens": m * self.block_tokens,
                "pending_cow": (cow_src, fresh[0]) if full_hit else None,
            }
            self.prefix_hits += m
            self.prefix_misses += max(0, len(hashes) - m)
        self._update_gauge()
        return list(blocks)

    def reserve_info(self, seq_id: str) -> Dict[str, Any]:
        """Reservation metadata recorded by :meth:`try_reserve`:
        ``hits`` / ``misses`` (prefix blocks), ``cached_tokens`` (prompt
        positions whose K/V needs no prefill), ``pending_cow``."""
        with self._lock:
            meta = self._meta.get(seq_id)
            return dict(meta) if meta else {
                "hits": 0, "misses": 0, "cached_tokens": 0,
                "pending_cow": None}

    # -- copy-on-write -----------------------------------------------------
    def take_pending_cow(self, seq_id: str) -> Optional[Tuple[int, int]]:
        """The (src, dst) block copy a full-prefix-hit reservation owes
        before its first write, or None. The caller copies src -> dst on
        device, then calls :meth:`cow_done` to release the src pin."""
        with self._lock:
            meta = self._meta.get(seq_id)
            return meta["pending_cow"] if meta else None

    def cow_done(self, seq_id: str) -> None:
        """Mark the pending copy complete: unpin the source block and
        count the copy."""
        with self._lock:
            meta = self._meta.get(seq_id)
            if not meta or not meta["pending_cow"]:
                return
            src, _dst = meta["pending_cow"]
            meta["pending_cow"] = None
            self.cow_copies += 1
            self._drop(src)
        self._update_gauge()

    def prepare_write(self, seq_id: str, block_index: int
                      ) -> Optional[Tuple[int, int]]:
        """Write barrier: make the block at position ``block_index`` of
        ``seq_id``'s lease writable.

        Refcount 1: de-index it (the content is about to diverge from
        its hash, and de-indexing inside the lock closes the race with a
        concurrent reservation matching it) and return None — write in
        place. Refcount > 1: allocate a fresh block, swap it into the
        lease, release the shared one, and return ``(src, dst)`` for the
        caller's device copy (counted as a CoW copy). Raises when no
        block can be reclaimed — admission should have left headroom."""
        with self._lock:
            blocks = self._leases.get(seq_id)
            if blocks is None:
                raise KeyError(f"sequence {seq_id!r} holds no blocks")
            src = blocks[block_index]
            if self._refcount.get(src, 0) <= 1:
                self._deindex(src)
                return None
            dst = self._take_fresh()
            if dst is None:
                raise RuntimeError(
                    f"copy-on-write for {seq_id!r} found no reclaimable "
                    "block; reservation accounting is broken")
            self._refcount[dst] = 1
            blocks[block_index] = dst
            self.cow_copies += 1
            self._drop(src)
        self._update_gauge()
        return (src, dst)

    # -- prefix index ------------------------------------------------------
    def register_prefix(self, seq_id: str, hashes: Sequence[str]) -> int:
        """Index ``seq_id``'s leading blocks under their chained hashes
        (called once the prompt's K/V is fully materialized). Blocks
        whose hash is already indexed elsewhere — or that are themselves
        already indexed — are skipped; returns how many were newly
        indexed."""
        added = 0
        with self._lock:
            self._tick += 1
            blocks = self._leases.get(seq_id, ())
            for i, h in enumerate(hashes):
                if i >= len(blocks):
                    break
                b = blocks[i]
                if h in self._index or b in self._block_hash:
                    continue
                self._index[h] = b
                self._block_hash[b] = h
                # parent link + depth make the chain walkable from its
                # tail — what resident_chains() advertises fleet-wide
                self._hmeta[h] = {
                    "parent": hashes[i - 1] if i else None,
                    "depth": i + 1, "hits": 0, "last_use": self._tick}
                added += 1
        return added

    def block_refcount(self, block: int) -> int:
        with self._lock:
            return self._refcount.get(block, 0)

    def resident_chains(self, top_k: int = 8) -> List[Dict[str, Any]]:
        """Top-K summary of the resident prefix chains — the replica's
        :class:`~mmlspark_tpu.serve.affinity.PrefixDigest` source.

        A chain is a maximal run of indexed blocks whose WHOLE ancestor
        line is still resident (a chain with an evicted ancestor can
        never be matched by :meth:`try_reserve`, so it is not
        advertised). Each entry carries the tail (deepest) hash, the
        full walkable hash list, the depth in blocks, the tail block's
        live lease count, the chain's hit count, and its last-use tick
        (a monotonic reservation counter, not wall time). Ranked
        hottest-first: (hits, last_use) descending.
        """
        if top_k <= 0:
            return []
        with self._lock:
            resident = set(self._index)
            parents = set()
            for rh in resident:
                hm = self._hmeta.get(rh)
                if hm and hm.get("parent") in resident:
                    parents.add(hm["parent"])
            out: List[Dict[str, Any]] = []
            for tail in resident - parents:
                walk: List[str] = []
                h: Optional[str] = tail
                while h is not None and h in resident:
                    walk.append(h)
                    hm = self._hmeta.get(h)
                    h = hm.get("parent") if hm else None
                if h is not None:
                    continue      # broken chain: an ancestor was evicted
                walk.reverse()
                hm = self._hmeta.get(tail) or {}
                out.append({
                    "chain": tail, "depth": len(walk), "hashes": walk,
                    "leases": self._refcount.get(
                        self._index.get(tail, -1), 0),
                    "hits": int(hm.get("hits", 0)),
                    "last_use": int(hm.get("last_use", 0))})
            out.sort(key=lambda c: (-c["hits"], -c["last_use"],
                                    -c["depth"], c["chain"]))
            return out[:int(top_k)]

    # -- release -----------------------------------------------------------
    def free(self, seq_id: str) -> int:
        """Release ``seq_id``'s shares the moment it finishes (or dies):
        every held block drops one refcount — shared prefix blocks
        survive for their other holders, and refcount-0 indexed blocks
        park in the cached pool instead of the free list. Idempotent (0
        when nothing was held)."""
        with self._lock:
            blocks = self._leases.pop(seq_id, None)
            meta = self._meta.pop(seq_id, None)
            if blocks:
                for b in blocks:
                    self._drop(b)
            if meta and meta.get("pending_cow"):
                self._drop(meta["pending_cow"][0])   # unpin the src
        if not blocks:
            return 0
        self._update_gauge()
        return len(blocks)

    def blocks_for(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._leases.get(seq_id, ()))

    def block_table(self, seq_id: str, width: int) -> np.ndarray:
        """``seq_id``'s lease padded to ``width`` with the reserved
        scratch block — one row of the decode program's block-table
        operand."""
        blocks = self.blocks_for(seq_id)
        if len(blocks) > width:
            raise ValueError(
                f"{seq_id!r} holds {len(blocks)} blocks > table width "
                f"{width}")
        row = np.full((width,), RESERVED_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    @property
    def leasable_blocks(self) -> int:
        """Blocks a sequence can actually hold (excludes scratch)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        """Blocks a reservation can draw on: truly free plus refcount-0
        cached prefix blocks (reclaimed LRU-first on demand)."""
        with self._lock:
            return len(self._free) + len(self._cached)

    @property
    def cached_blocks(self) -> int:
        """Refcount-0 blocks parked with live prefix content."""
        with self._lock:
            return len(self._cached)

    @property
    def used_blocks(self) -> int:
        """Distinct blocks held by at least one sequence (a shared
        prefix block counts once, however many sequences ride it)."""
        with self._lock:
            return len(self._refcount)

    @property
    def active_sequences(self) -> int:
        with self._lock:
            return len(self._leases)

    def occupancy(self) -> float:
        """Held fraction of the leasable arena (the KV-occupancy gauge
        and report column)."""
        return self.used_blocks / max(1, self.leasable_blocks)

    def check_conservation(self) -> bool:
        """Ledger invariant (the property-fuzz assertion): every
        leasable block is in exactly ONE of free / cached / refcounted,
        and the scratch block is in none of them."""
        with self._lock:
            held = set(self._refcount)
            free = set(self._free)
            cached = set(self._cached)
            all_blocks = held | free | cached
            return (len(self._free) + len(self._cached) + len(held)
                    == self.num_blocks - 1
                    and len(all_blocks) == self.num_blocks - 1
                    and RESERVED_BLOCK not in all_blocks
                    and all(self._index.get(h) == b and
                            self._block_hash.get(b) == h
                            for b, h in list(self._cached.items())))

    # -- donation round-trip ----------------------------------------------
    def swap(self, arena_k, arena_v, scale_k=None, scale_v=None) -> None:
        """Store the (donated-and-returned) arena set back after a
        prefill/decode program call; the old references are dead buffers
        on donating backends."""
        self.arena_k = arena_k
        self.arena_v = arena_v
        if scale_k is not None:
            self.scale_k = scale_k
        if scale_v is not None:
            self.scale_v = scale_v

    def stats(self) -> Dict[str, Any]:
        # the resident-chain digest rides the stats dict as a structured
        # (non-numeric) value: the scraper's fleet totals and registry
        # gauges skip it, the affinity layer picks it out by key
        chains = self.resident_chains(
            int(mmlconfig.get("generate.advertise_top_k")))
        with self._lock:
            used = len(self._refcount)
            return {
                "resident_chains": chains,
                # hash-seed params: a digest consumer re-derives the
                # prompt's chain with the SAME (model, dtype, granule)
                # seed, so advertise them next to the chains
                "kv_dtype": self.dtype.name,
                "blocks": self.num_blocks,
                "block_tokens": self.block_tokens,
                "used_blocks": used,
                "free_blocks": len(self._free) + len(self._cached),
                "cached_blocks": len(self._cached),
                "sequences": len(self._leases),
                "occupancy": used / max(1, self.num_blocks - 1),
                "arena_bytes": self.arena_bytes(),
                "arena_shard_bytes": self.arena_shard_bytes(),
                "unquantized_arena_bytes": self.unquantized_arena_bytes(),
                "quantized": float(self.quantized),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "cow_copies": self.cow_copies,
                "prefix_evictions": self.prefix_evictions,
            }

    def _update_gauge(self) -> None:
        if metrics.metrics_enabled():
            metrics.gauge("generate.kv_occupancy").set(self.occupancy())
            metrics.gauge("generate.kv_cached_blocks").set(
                float(self.cached_blocks))
