"""Paged KV-cache arena for the generative serving lane.

Autoregressive decode is memory-bound on the key/value history: a naive
per-sequence ``(max_seq_len, heads, head_dim)`` allocation wastes HBM on
short sequences and fragments it as sequences of different lengths join
and leave the in-flight batch. This module is the vLLM-style answer
(PAPERS.md: PagedAttention) sized for this framework: ONE fixed arena of
``num_blocks`` fixed-size blocks per layer, allocated once at lane
warm-up, with a host-side free list handing ``ceil(len / block_tokens)``
blocks to each admitted sequence and reclaiming them the step the
sequence finishes.

Contracts the rest of the lane builds on:

- **Fixed footprint.** The arena never grows. Admission that cannot get
  its blocks is SHED (the server raises a retryable ``ServerOverloaded``)
  — decode never OOMs mid-sequence, because a sequence's full block
  budget (prompt + ``max_new_tokens``) is reserved up front.
- **Block 0 is reserved scratch.** Decode programs run at a fixed batch
  bucket; lanes without a live sequence route their (masked, garbage)
  writes to block 0 so the compiled program never branches on occupancy.
  Real sequences are handed blocks ``1..num_blocks-1`` only.
- **Donation round-trip.** The decode/prefill executables donate the
  arena buffers (in-place update on TPU); callers pass
  ``arena_k``/``arena_v`` in and MUST store the returned pair back via
  :meth:`swap` before the next step.
- **Budget accounting.** ``arena_bytes()`` is charged to the owning
  :class:`~mmlspark_tpu.serve.registry.ModelEntry` so the registry's
  ``runtime.device_cache_mb`` LRU sees scoring params and decode arena
  as one HBM tenant set (``generate.arena_mb`` sizes the arena itself;
  0 derives it from ``generate.max_sequences`` x ``generate.max_seq_len``).

This module is the ONE sanctioned device-allocation site in ``serve/``
(lint Rule 10): everything else goes through the registry or marks an
explicit ``# lint: allow-alloc``.
"""
from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.observability import memory as devmem
from mmlspark_tpu.observability import metrics
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.kvcache")

RESERVED_BLOCK = 0  # scratch target for masked decode lanes; never leased


def blocks_needed(tokens: int, block_tokens: int) -> int:
    """Blocks covering ``tokens`` positions at the arena granule."""
    return max(1, math.ceil(int(tokens) / int(block_tokens)))


class KVCacheManager:
    """Fixed paged KV arena + host-side block ledger (thread-safe).

    The device arrays are plain unsharded buffers shaped
    ``(layers, num_blocks, block_tokens, heads, head_dim)``; the ledger
    (free list + per-sequence leases) lives entirely on the host so
    reserve/free never touch the device.
    """

    def __init__(self, *, layers: int, heads: int, head_dim: int,
                 num_blocks: int, block_tokens: int, dtype=np.float32):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block {RESERVED_BLOCK} is "
                f"reserved scratch), got {num_blocks}")
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self.dtype = np.dtype(dtype)
        import jax.numpy as jnp
        shape = (self.layers, self.num_blocks, self.block_tokens,
                 self.heads, self.head_dim)
        self.arena_k = jnp.zeros(shape, self.dtype)
        self.arena_v = jnp.zeros(shape, self.dtype)
        self._lock = threading.Lock()
        # LIFO free list: recently-freed blocks are re-leased first, which
        # keeps the hot working set compact in HBM
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._leases: Dict[str, List[int]] = {}
        self._update_gauge()

    # -- sizing ------------------------------------------------------------
    @classmethod
    def from_config(cls, *, layers: int, heads: int, head_dim: int,
                    dtype=np.float32) -> "KVCacheManager":
        """Size the arena from the ``generate.*`` config namespace:
        ``generate.arena_mb`` when set, else enough blocks for
        ``generate.max_sequences`` sequences of ``generate.max_seq_len``
        tokens (plus the reserved scratch block)."""
        bt = int(mmlconfig.get("generate.kv_block_tokens"))
        arena_mb = float(mmlconfig.get("generate.arena_mb"))
        if arena_mb > 0:
            per_block = devmem.nbytes_of((2, layers, bt, heads, head_dim),
                                         dtype)
            num_blocks = max(2, int(arena_mb * 1e6 // per_block))
        else:
            seqs = int(mmlconfig.get("generate.max_sequences"))
            max_len = int(mmlconfig.get("generate.max_seq_len"))
            num_blocks = 1 + seqs * blocks_needed(max_len, bt)
        return cls(layers=layers, heads=heads, head_dim=head_dim,
                   num_blocks=num_blocks, block_tokens=bt, dtype=dtype)

    def arena_bytes(self) -> int:
        """Total HBM footprint of both arenas (charged to the owning
        registry entry so the device-cache LRU accounts for it); the
        arithmetic itself lives in the HBM ledger (lint Rule 11)."""
        return 2 * devmem.nbytes_of(
            (self.layers, self.num_blocks, self.block_tokens,
             self.heads, self.head_dim), self.dtype)

    # -- ledger ------------------------------------------------------------
    def try_reserve(self, seq_id: str, tokens: int) -> Optional[List[int]]:
        """Lease blocks covering ``tokens`` positions for ``seq_id``.
        Returns the block ids (stable for the sequence's lifetime) or
        None when the free list cannot cover the ask — the caller sheds
        the request (retryable) instead of queueing into an OOM."""
        n = blocks_needed(tokens, self.block_tokens)
        with self._lock:
            if seq_id in self._leases:
                raise ValueError(f"sequence {seq_id!r} already holds blocks")
            if len(self._free) < n:
                return None
            blocks = [self._free.pop() for _ in range(n)]
            self._leases[seq_id] = blocks
        self._update_gauge()
        return list(blocks)

    def free(self, seq_id: str) -> int:
        """Return ``seq_id``'s blocks to the free list the moment it
        finishes; idempotent (0 when nothing was held)."""
        with self._lock:
            blocks = self._leases.pop(seq_id, None)
            if blocks:
                self._free.extend(blocks)
        if not blocks:
            return 0
        self._update_gauge()
        return len(blocks)

    def blocks_for(self, seq_id: str) -> List[int]:
        with self._lock:
            return list(self._leases.get(seq_id, ()))

    def block_table(self, seq_id: str, width: int) -> np.ndarray:
        """``seq_id``'s lease padded to ``width`` with the reserved
        scratch block — one row of the decode program's block-table
        operand."""
        blocks = self.blocks_for(seq_id)
        if len(blocks) > width:
            raise ValueError(
                f"{seq_id!r} holds {len(blocks)} blocks > table width "
                f"{width}")
        row = np.full((width,), RESERVED_BLOCK, np.int32)
        row[:len(blocks)] = blocks
        return row

    @property
    def leasable_blocks(self) -> int:
        """Blocks a sequence can actually hold (excludes scratch)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._leases.values())

    @property
    def active_sequences(self) -> int:
        with self._lock:
            return len(self._leases)

    def occupancy(self) -> float:
        """Leased fraction of the leasable arena (the KV-occupancy gauge
        and report column)."""
        return self.used_blocks / max(1, self.leasable_blocks)

    # -- donation round-trip ----------------------------------------------
    def swap(self, arena_k, arena_v) -> None:
        """Store the (donated-and-returned) arena pair back after a
        prefill/decode program call; the old references are dead buffers
        on donating backends."""
        self.arena_k = arena_k
        self.arena_v = arena_v

    def stats(self) -> Dict[str, float]:
        with self._lock:
            used = sum(len(b) for b in self._leases.values())
            return {
                "blocks": self.num_blocks,
                "block_tokens": self.block_tokens,
                "used_blocks": used,
                "free_blocks": len(self._free),
                "sequences": len(self._leases),
                "occupancy": used / max(1, self.num_blocks - 1),
                "arena_bytes": self.arena_bytes(),
            }

    def _update_gauge(self) -> None:
        if metrics.metrics_enabled():
            metrics.gauge("generate.kv_occupancy").set(self.occupancy())
