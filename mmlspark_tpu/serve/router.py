"""Fleet router: spread requests over N replicas, survive a dying one.

The cross-replica layer the per-process primitives (bounded-queue shed,
``CircuitBreaker``, ``RetryPolicy``, drain) were built for. One
:class:`Router` fronts N replicas — anything satisfying the ``Replica``
protocol below: in-process :class:`~mmlspark_tpu.serve.fleet.
InProcessReplica` handles or subprocess HTTP backends
(:class:`HttpReplica`) — and gives callers ONE ``submit`` with fleet
semantics:

- **Weighted spread**: replicas are picked by smooth weighted round-robin
  (the nginx algorithm: deterministic, no RNG, interleaves weights
  evenly), over the READY set only. Weights are the rollout traffic
  lever — ``set_weight(name, 0.0)`` shifts a replica out of rotation
  without touching its in-flight work.
- **Health-checked**: every replica is probed through its ``health()``
  (the ``/healthz`` live/ready split) and guarded by a per-replica
  :class:`CircuitBreaker` — repeated submit failures trip it open, the
  single half-open probe slot re-admits it, and ``probe()`` (or the
  background prober) flips readiness the moment a replica reports
  draining, BEFORE it stops being alive.
- **Automatic failover**: a request in flight on a dying replica
  (``ReplicaUnavailable``, a connection error, a breaker trip) is retried
  on a different replica via ``RetryPolicy`` — same ``trace_id``, same
  absolute deadline (the remaining budget, not a fresh one); a replica
  already tried this request is excluded. ``fleet.failover_attempts``
  bounds the chain (default 2 = one failover).
- **Consolidated shed**: a replica shedding (``ServerOverloaded``) is not
  a failover — the router immediately offers the request to the next
  ready replica, and only when EVERY candidate shed does the caller see
  one consolidated ``ServerOverloaded`` whose ``retry_after`` is the
  MINIMUM across replicas (come back when the soonest frees up).
- **Per-tenant fairness**: admission runs through
  :class:`WeightedFairAdmission` — stride-scheduling virtual time plus a
  weighted in-flight quota over the fleet's summed capacity — so one hot
  tenant sheds (retryable ``TenantThrottled``) while everyone else keeps
  admitting. Layered ABOVE the per-replica bounded-queue shed path, not
  instead of it.

Every raw cross-replica call lives in this module — lint Rule 8 flags
direct ``replica.submit(...)`` elsewhere in ``serve/`` so nothing routes
around the breaker/retry wrappers (escape: ``# lint:
allow-direct-replica``).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.reliability.breaker import CircuitBreaker, CircuitOpen
from mmlspark_tpu.reliability.retry import RetryPolicy
from mmlspark_tpu.serve.affinity import AffinityHint, AffinityState
from mmlspark_tpu.serve.server import (
    RequestExpired, ServeError, ServerClosed, ServerOverloaded,
    _mint_trace_id, _Twin,
)
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.router")


class ReplicaUnavailable(ServeError):
    """The replica cannot take this request at the transport level — dead
    process, refused connection, torn socket. Retryable by contract: the
    router's failover policy sends the SAME request (same trace_id, same
    deadline) to a different replica."""
    retryable = True


class TenantThrottled(ServerOverloaded):
    """Admission rejected by the per-tenant fairness layer, not by any
    replica: this tenant is over its weighted share of fleet capacity
    while others still have headroom. Retryable (back off and resubmit),
    and deliberately a :class:`ServerOverloaded` subclass so existing
    shed handling (HTTP 503 mapping, retry classification) applies."""

    def __init__(self, tenant: str, inflight: int, share: int,
                 retry_after: Optional[float] = None):
        super().__init__(
            f"tenant {tenant!r} over fair share ({inflight} in flight, "
            f"share {share}); retry with backoff", retry_after=retry_after)
        self.tenant = tenant


class _AllShed(ServeError):
    """Internal: every candidate replica shed this request. NOT retryable
    — re-spinning the same saturated fleet immediately is how overload
    becomes an outage; the caller gets the consolidated overload and its
    own retry layer backs off."""
    retryable = False

    def __init__(self, sheds: List[Tuple[str, ServerOverloaded]]):
        super().__init__("all replicas shed")
        self.sheds = sheds


def parse_tenant_weights(text: str) -> Dict[str, float]:
    """``fleet.tenant_weights`` config ("gold=3,free=1") -> dict."""
    out: Dict[str, float] = {}
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, val = part.partition("=")
        if not sep or not name.strip():
            raise ValueError(
                f"tenant weights: expected NAME=WEIGHT, got {part!r}")
        w = float(val)
        if w <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        out[name.strip()] = w
    return out


class WeightedFairAdmission:
    """Stride-scheduling fairness + weighted in-flight quotas per tenant.

    Two mechanisms, one invariant ("a hot tenant cannot starve the
    rest"):

    - **Quota** (the enforcement): a tenant may hold at most
      ``ceil(weight_share * capacity)`` rows in flight, where the share
      is computed over the tenants ACTIVE right now — an idle fleet lets
      one tenant use everything; contention shrinks everyone to their
      weighted share. Over-quota admits raise :class:`TenantThrottled`.
    - **Virtual time** (the observability): classic stride scheduling —
      each admitted row advances the tenant's virtual time by
      ``rows / weight`` — so ``stats()`` exposes exactly how far ahead
      of its fair share every tenant is running. The chaos harness and
      the report read it; operators tune weights from it.

    Pure logic under one lock; no threads, no clock.
    """

    def __init__(self, capacity_rows: int,
                 weights: Optional[Dict[str, float]] = None,
                 default_weight: Optional[float] = None):
        if capacity_rows < 1:
            raise ValueError(
                f"capacity_rows must be >= 1, got {capacity_rows}")
        self.capacity_rows = int(capacity_rows)
        # the configured quota before any autopilot tightening: relax
        # actions ramp capacity back toward this, never past it
        self.baseline_rows = int(capacity_rows)
        self.weights = dict(weights or {})
        self.default_weight = float(
            default_weight if default_weight is not None
            else mmlconfig.get("fleet.tenant_default_weight"))
        if self.default_weight <= 0:
            raise ValueError("default tenant weight must be > 0")
        self._lock = threading.Lock()
        self._inflight: Dict[str, int] = {}
        self._vtime: Dict[str, float] = {}
        self._throttled = metrics.counter("fleet.tenant_throttled")

    def weight(self, tenant: str) -> float:
        return float(self.weights.get(tenant, self.default_weight))

    def share(self, tenant: str) -> int:
        """This tenant's current in-flight quota in rows (>= 1)."""
        with self._lock:
            return self._share_locked(tenant)

    def _share_locked(self, tenant: str) -> int:
        active = set(k for k, v in self._inflight.items() if v > 0)
        active.add(tenant)
        total = sum(self.weight(t) for t in active)
        frac = self.weight(tenant) / total if total > 0 else 1.0
        return max(1, int(np.ceil(frac * self.capacity_rows)))

    def admit(self, tenant: str, rows: int) -> None:
        """Charge ``rows`` to ``tenant`` or raise :class:`TenantThrottled`.
        Callers MUST pair every successful admit with :meth:`release` (the
        router does, in a finally)."""
        with self._lock:
            held = self._inflight.get(tenant, 0)
            share = self._share_locked(tenant)
            if held + rows > share:
                self._throttled.inc()
                if events.recording_enabled():
                    events.emit("fleet", "tenant_throttled", tenant=tenant,
                                inflight=held, rows=rows, share=share)
                raise TenantThrottled(tenant, held, share,
                                      retry_after=float(
                                          mmlconfig.get(
                                              "serving.retry_after_s")))
            self._inflight[tenant] = held + rows
            self._vtime[tenant] = self._vtime.get(tenant, 0.0) \
                + rows / self.weight(tenant)

    def release(self, tenant: str, rows: int) -> None:
        with self._lock:
            held = self._inflight.get(tenant, 0)
            self._inflight[tenant] = max(0, held - rows)

    def set_capacity(self, capacity_rows: int) -> None:
        """Adaptive-admission actuator (lint Rule 15): resize the fleet
        quota all tenant shares are computed from. Tightening under burn
        turns blind per-replica sheds into ordered per-tenant throttles;
        relaxing ramps back toward :attr:`baseline_rows`. In-flight work
        is untouched — only future admits see the new shares."""
        cap = int(capacity_rows)
        if cap < 1:
            raise ValueError(f"capacity_rows must be >= 1, got {cap}")
        with self._lock:
            old = self.capacity_rows
            self.capacity_rows = cap
        if events.recording_enabled():
            events.emit("fleet", "capacity", capacity_rows=cap,
                        previous=old)

    def stats(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            base = min(self._vtime.values()) if self._vtime else 0.0
            return {t: {"inflight": self._inflight.get(t, 0),
                        "weight": self.weight(t),
                        "vtime_lead": round(self._vtime.get(t, 0.0) - base,
                                            4)}
                    for t in sorted(set(self._inflight) | set(self._vtime))}


class _Handle:
    """Router-side state for one replica: weight, readiness, breaker,
    smooth-WRR accumulator."""

    __slots__ = ("replica", "name", "weight", "current", "ready", "state",
                 "breaker", "routed", "inflight")

    def __init__(self, replica, breaker: CircuitBreaker):
        self.replica = replica
        self.name = replica.name
        self.weight = 1.0
        self.current = 0.0          # smooth-WRR accumulator
        self.ready = True           # until a probe says otherwise
        self.state = "unknown"
        self.breaker = breaker
        self.routed = metrics.Counter(f"fleet.routed.{self.name}")
        self.inflight = 0           # requests inside _call_replica now


class Router:
    """Health-checked weighted router over N ``Replica`` backends.

    The protocol a backend must satisfy (duck-typed)::

        name: str
        submit(model, x, deadline_ms=None, trace_id="") -> np.ndarray
        health() -> {"live": bool, "ready": bool, "state": str}
        capacity_rows: int          # admission bound (fairness sizing)

    ``clock``/``sleep`` are injectable so failover and deadline tests run
    without wall time; probes are driven either manually (:meth:`probe`)
    or by :meth:`start_prober`'s background thread.
    """

    def __init__(self, replicas: Sequence, *,
                 failover_attempts: Optional[int] = None,
                 failover_delay_s: Optional[float] = None,
                 capacity_rows: Optional[int] = None,
                 tenant_weights: Optional[Dict[str, float]] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.clock = clock if clock is not None else events.perf
        self._sleep = sleep
        self._lock = threading.Lock()
        self._handles: "Dict[str, _Handle]" = {}
        # kept so add_replica() builds breakers identical to these
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        for r in replicas:
            if r.name in self._handles:
                raise ValueError(f"duplicate replica name {r.name!r}")
            breaker = CircuitBreaker(
                f"fleet.{r.name}", failure_threshold=breaker_failures,
                reset_timeout_s=breaker_reset_s, clock=self.clock)
            self._handles[r.name] = _Handle(r, breaker)
        attempts = int(failover_attempts if failover_attempts is not None
                       else mmlconfig.get("fleet.failover_attempts"))
        if attempts < 1:
            raise ValueError(f"failover_attempts must be >= 1, "
                             f"got {attempts}")
        delay = float(failover_delay_s if failover_delay_s is not None
                      else mmlconfig.get("fleet.failover_delay_s"))
        kwargs = {} if sleep is None else {"sleep": sleep}
        self.failover_policy = RetryPolicy(
            max_attempts=attempts, base_delay=delay, jitter=0.0,
            name="fleet.failover", clock=self.clock, **kwargs)
        if capacity_rows is None:
            capacity_rows = int(mmlconfig.get("fleet.capacity_rows"))
        if capacity_rows <= 0:
            capacity_rows = sum(
                int(getattr(h.replica, "capacity_rows", 0)) or 256
                for h in self._handles.values())
        if tenant_weights is None:
            tenant_weights = parse_tenant_weights(
                str(mmlconfig.get("fleet.tenant_weights")))
        self.fairness = WeightedFairAdmission(capacity_rows, tenant_weights)
        # per-instance twins (like Server's counters): stats() must read
        # THIS router's counts even when several routers share the
        # process-wide metrics registry (chaos runs two in a row)
        self._failovers = _Twin("fleet.failovers")
        self._all_shed = _Twin("fleet.all_shed")
        # prefix/session affinity for the generate lane (serve/affinity.py;
        # docs/SERVING.md "fleet as one cache"). With no digests published
        # yet (no scraper) and no session keys, picks reduce to pure WRR.
        self.affinity: Optional[AffinityState] = (
            AffinityState()
            if bool(mmlconfig.get("fleet.affinity_enabled")) else None)
        self._prober: Optional[threading.Thread] = None
        self._prober_stop = threading.Event()
        # chaos sets this to a list: the router then appends the serving
        # replica's name per routed request — the deterministic schedule
        # two same-seed runs must reproduce bit-for-bit
        self.route_log: Optional[List[str]] = None

    # -- replica set -------------------------------------------------------
    def replica_names(self) -> List[str]:
        return sorted(self._handles)

    def add_replica(self, replica, *, weight: float = 1.0) -> None:
        """Scale-up actuator (lint Rule 15): put a new backend into
        rotation with its own fresh breaker, same knobs as the founding
        set. The next :meth:`_pick` can route to it immediately."""
        if weight < 0:
            raise ValueError(f"weight must be >= 0, got {weight}")
        with self._lock:
            if replica.name in self._handles:
                raise ValueError(
                    f"duplicate replica name {replica.name!r}")
            breaker = CircuitBreaker(
                f"fleet.{replica.name}",
                failure_threshold=self._breaker_failures,
                reset_timeout_s=self._breaker_reset_s, clock=self.clock)
            h = _Handle(replica, breaker)
            h.weight = float(weight)
            self._handles[replica.name] = h
        if events.recording_enabled():
            events.emit("fleet", "add_replica", replica=replica.name,
                        weight=weight)

    def remove_replica(self, name: str) -> None:
        """Scale-down actuator (lint Rule 15): take a backend out of the
        rotation entirely. In-flight work on it is untouched — callers
        drain the backend themselves (``Fleet.scale_down`` does)."""
        with self._lock:
            if name not in self._handles:
                raise KeyError(f"unknown replica {name!r}")
            if len(self._handles) == 1:
                raise ValueError(
                    "cannot remove the last replica from the router")
            del self._handles[name]
        if self.affinity is not None:
            self.affinity.forget(name)
        if events.recording_enabled():
            events.emit("fleet", "remove_replica", replica=name)

    def set_weight(self, name: str, weight: float) -> None:
        """Traffic share for one replica (0.0 = out of rotation — the
        rollout shift lever). In-flight work is untouched."""
        if weight < 0:
            raise ValueError(f"weight must be >= 0, got {weight}")
        with self._lock:
            h = self._handles[name]
            h.weight = float(weight)
            h.current = 0.0
        if events.recording_enabled():
            events.emit("fleet", "weight", replica=name, weight=weight)

    def reset_breaker(self, name: str) -> None:
        """Force-close one replica's breaker (operator/supervisor lever).
        A replica that was DOWN long enough to trip its breaker open and
        then came back healthy would otherwise wait out the full cooldown
        before taking traffic; the supervisor calls this on a verified
        warm restart so re-registration is immediate."""
        self._handles[name].breaker.reset()

    def _pick(self, exclude: frozenset,
              hint: Optional[AffinityHint] = None) -> Optional[_Handle]:
        """Smooth weighted round-robin over ready, positive-weight,
        non-excluded replicas. Deterministic: same weights + same call
        sequence = same spread (the chaos schedule depends on this).

        With an affinity ``hint``, the SAFE candidate set is first
        narrowed by :meth:`AffinityState.select` — session stickiness,
        then expected prefix-hit depth — and the WRR spread runs over
        the narrowed pool (the tie-break). The safety filter above is
        non-negotiable: affinity never resurrects an excluded, unready,
        or zero-weight replica, and on failover the survivors are
        re-scored with the dead replica in ``exclude``.

        Overload overrides affinity (bounded load): when every replica
        affinity picked is carrying more than
        ``fleet.affinity_spill_factor`` times the candidate-mean
        in-flight count (plus one — idle fleets never spill), the pick
        SPILLS back to the full WRR pool. A warm cache is never worth a
        hot spot, and a Zipf-heavy trace would otherwise convoy behind
        the one replica that owns the hottest chain."""
        mode, depth = "wrr", 0
        with self._lock:
            cands = [h for h in self._handles.values()
                     if h.ready and h.weight > 0 and h.name not in exclude]
        if not cands:
            return None
        pool = cands
        if hint is not None and self.affinity is not None:
            names, mode, depth = self.affinity.select(
                [h.name for h in cands], hint)
            chosen = [h for h in cands if h.name in set(names)]
            if chosen and mode != "wrr":
                factor = float(mmlconfig.get("fleet.affinity_spill_factor"))
                if factor > 0:
                    with self._lock:
                        cap = factor * (
                            sum(h.inflight for h in cands) / len(cands) + 1)
                        chosen = [h for h in chosen if h.inflight + 1 <= cap]
                        if not chosen:
                            # spill AWAY from the loaded leader, not back
                            # onto it: the cool replica that absorbs this
                            # miss caches the chain and advertises it —
                            # hot chains grow replicas under pressure
                            chosen = [h for h in cands
                                      if h.inflight + 1 <= cap]
                if not chosen:
                    chosen = cands
                    mode, depth = "wrr", 0
                elif mode != "wrr" and not set(names) & {
                        h.name for h in chosen}:
                    mode, depth = "wrr", 0
                if mode == "wrr":
                    self.affinity.observe_spill()
            if chosen:
                pool = chosen
        with self._lock:
            total = sum(h.weight for h in pool)
            for h in pool:
                h.current += h.weight
            best = max(pool, key=lambda h: (h.current, h.name))
            best.current -= total
        if hint is not None and self.affinity is not None:
            self.affinity.observe_route(best.name, mode, depth)
        return best

    # -- health ------------------------------------------------------------
    def probe(self) -> Dict[str, str]:
        """Probe every replica's ``health()`` once; flip readiness and
        feed the breakers (an unreachable replica counts a failure, a
        healthy answer counts a success so half-open closes). Returns
        ``{name: state}``. Deterministic given the replicas' answers —
        tests and the chaos harness drive this instead of the thread."""
        states: Dict[str, str] = {}
        for h in list(self._handles.values()):
            try:
                health = h.replica.health()
            except Exception as e:
                health = {"live": False, "ready": False, "state": "dead"}
                logger.warning("probe %s failed: %s", h.name, e)
            ready = bool(health.get("ready")) and bool(health.get("live"))
            state = str(health.get("state", "dead"))
            prev = h.state
            with self._lock:
                h.ready = ready
                h.state = state
            if ready:
                # a ready answer is the health probe succeeding: close a
                # tripped breaker through its half-open slot so traffic
                # returns without waiting for a live request to probe
                if h.breaker.state != "closed" and h.breaker.allow():
                    h.breaker.record_success()
            else:
                h.breaker.record_failure()
            if prev != state and events.recording_enabled():
                events.emit("fleet", "probe", replica=h.name, state=state,
                            prev=prev, ready=ready)
            states[h.name] = state
        if metrics.metrics_enabled():
            metrics.gauge("fleet.replicas_ready").set(
                sum(1 for h in self._handles.values() if h.ready))
        return states

    def start_prober(self, interval_s: Optional[float] = None) -> None:
        """Background health probing every ``fleet.probe_interval_s``."""
        if self._prober is not None:
            return
        poll = float(interval_s if interval_s is not None
                     else mmlconfig.get("fleet.probe_interval_s"))

        def run() -> None:
            while not self._prober_stop.wait(poll):
                try:
                    self.probe()
                except Exception as e:  # prober must outlive one bad round
                    logger.warning("prober round failed: %s", e)

        self._prober = threading.Thread(
            target=run, name="mmlspark-tpu-fleet-prober", daemon=True)
        self._prober.start()

    def stop_prober(self) -> None:
        if self._prober is None:
            return
        self._prober_stop.set()
        self._prober.join(timeout=5)
        self._prober = None
        self._prober_stop = threading.Event()

    # -- routing -----------------------------------------------------------
    def submit(self, model: str, x, deadline_ms: Optional[float] = None,
               *, tenant: str = "default",
               trace_id: Optional[str] = None) -> np.ndarray:
        """Route one request: fairness admit -> pick replica -> call
        through its breaker -> failover once (``RetryPolicy``) if the
        replica dies under it. The ``trace_id`` and absolute deadline
        survive the whole chain."""
        arr = np.asarray(x)
        rows = int(arr.shape[0]) if arr.ndim > 1 else 1
        trace_id = trace_id or _mint_trace_id()
        deadline = None
        if deadline_ms is not None and deadline_ms > 0:
            deadline = self.clock() + deadline_ms / 1e3
        self.fairness.admit(tenant, rows)

        def call(h: _Handle, remaining_ms: Optional[float]):
            return h.replica.submit(  # lint: allow-direct-replica
                model, x, deadline_ms=remaining_ms, trace_id=trace_id)

        try:
            return self._route(model, call, trace_id, deadline)
        finally:
            self.fairness.release(tenant, rows)

    def submit_generate(self, model: str, prompt,
                        max_new_tokens: Optional[int] = None, *,
                        temperature: float = 0.0, top_k: int = 0,
                        seed: int = 0, eos_id: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        tenant: str = "default",
                        session: Optional[str] = None,
                        trace_id: Optional[str] = None) -> Dict:
        """Route one generation request with fleet semantics. Failover is
        a RESTART: generation state (KV pages, sampled tokens) dies with
        the replica, so the surviving replica replays the whole request
        from its prompt — and because sampling is seeded per (seed,
        position), the replayed stream is token-identical. Same
        ``trace_id`` and the REMAINING deadline ride the retry.

        Routing is prefix-affine (docs/SERVING.md "fleet as one
        cache"): the prompt's block-hash chain is scored against every
        READY replica's advertised digest, and a ``session`` key pins a
        multi-turn conversation to one replica via the consistent-hash
        ring — health, breakers, and overload always override both."""
        prompt = [int(t) for t in np.asarray(prompt).ravel()]
        trace_id = trace_id or _mint_trace_id()
        deadline = None
        if deadline_ms is not None and deadline_ms > 0:
            deadline = self.clock() + deadline_ms / 1e3
        hint = self.affinity.hint_for(model, prompt, session) \
            if self.affinity is not None else None
        self.fairness.admit(tenant, 1)

        def call(h: _Handle, remaining_ms: Optional[float]):
            return h.replica.submit_generate(  # lint: allow-direct-replica
                model, prompt, max_new_tokens, temperature=temperature,
                top_k=top_k, seed=seed, eos_id=eos_id,
                deadline_ms=remaining_ms, trace_id=trace_id)

        try:
            return self._route(model, call, trace_id, deadline,
                               kind="generate", hint=hint)
        finally:
            self.fairness.release(tenant, 1)

    def _route(self, model: str, call: Callable, trace_id: str,
               deadline: Optional[float], kind: str = "score",
               hint: Optional[AffinityHint] = None):
        tried: set = set()
        sheds: List[Tuple[str, ServerOverloaded]] = []
        try:
            for attempt in self.failover_policy.attempts():
                with attempt:
                    return self._route_once(model, call, trace_id,
                                            deadline, tried, sheds, kind,
                                            hint)
        except _AllShed:
            pass  # consolidated below
        except (ReplicaUnavailable, CircuitOpen, ConnectionError) as e:
            if sheds:
                pass  # some replicas shed, the rest died: still overload
            else:
                raise ReplicaUnavailable(
                    f"no healthy replica for {model!r} "
                    f"(tried {sorted(tried)}): {e}") from e
        # every candidate shed: ONE consolidated overload whose
        # retry_after is the minimum ask across replicas
        self._all_shed.inc()
        afters = [e.retry_after for _, e in sheds
                  if getattr(e, "retry_after", None) is not None]
        retry_after = min(afters) if afters else None
        if events.recording_enabled():
            events.emit("fleet", "all_shed", model=model, trace_id=trace_id,
                        replicas=[n for n, _ in sheds],
                        retry_after=retry_after)
        raise ServerOverloaded(
            f"all {len(sheds)} replica(s) shedding "
            f"({', '.join(n for n, _ in sheds) or 'none ready'}); "
            "retry with backoff", retry_after=retry_after) from None

    def _route_once(self, model: str, call: Callable, trace_id: str,
                    deadline: Optional[float], tried: set,
                    sheds: List[Tuple[str, ServerOverloaded]],
                    kind: str = "score",
                    hint: Optional[AffinityHint] = None):
        """One routing attempt: offer the request to ready replicas in WRR
        order. A shed moves on to the next candidate in THIS attempt; a
        dead replica raises so the failover policy retries (a fresh
        attempt, this replica excluded — and, with an affinity hint, the
        survivors re-scored by prefix depth so the warmest one wins the
        restart). ``call(handle, remaining_ms)`` performs the actual
        replica call — scoring and generation share this whole
        routing/failover/shed machinery."""
        while True:
            if deadline is not None and self.clock() >= deadline:
                raise RequestExpired(
                    f"deadline passed before a replica could answer "
                    f"(tried {sorted(tried)})")
            h = self._pick(frozenset(tried), hint)
            if h is None:
                if sheds:
                    raise _AllShed(sheds)
                raise ReplicaUnavailable(
                    f"no ready replica (of {len(self._handles)}) for "
                    f"{model!r}; tried {sorted(tried)}")
            remaining_ms = None
            if deadline is not None:
                remaining_ms = max((deadline - self.clock()) * 1e3, 0.001)
            with self._lock:
                h.inflight += 1     # the spill bound reads this
            try:
                out = self._call_replica(h, call, remaining_ms)
            except ServerOverloaded as e:
                # this replica is full/draining, not dead: same attempt,
                # next candidate (don't charge the failover budget)
                tried.add(h.name)
                sheds.append((h.name, e))
                continue
            except RequestExpired:
                raise  # the caller's deadline elapsed; retrying is futile
            except (KeyError, ValueError, TypeError):
                raise  # client error: same everywhere, don't failover
            except ServerClosed as e:
                self._mark_down(h, "closed")
                self._emit_failover(h, trace_id, e, kind)
                tried.add(h.name)
                raise ReplicaUnavailable(
                    f"replica {h.name} closed mid-request") from e
            except (ReplicaUnavailable, CircuitOpen, ConnectionError,
                    OSError) as e:
                # dying replica: mark it down, let the RetryPolicy give
                # this request its one failover on a healthy one
                self._mark_down(h, "dead")
                self._emit_failover(h, trace_id, e, kind)
                tried.add(h.name)
                raise
            finally:
                with self._lock:
                    h.inflight -= 1
            h.routed.inc()
            if self.route_log is not None:
                self.route_log.append(h.name)
            return out

    @staticmethod
    def _call_replica(h: _Handle, call: Callable,
                      remaining_ms: Optional[float]):
        """One raw replica call through its breaker. A replica that
        ANSWERS — even with a shed, an expired deadline, or a client
        error — is alive, so only transport-level failures feed the
        breaker's failure count; application answers record success."""
        answered: List[BaseException] = []

        def guarded():
            try:
                return call(h, remaining_ms)
            except (ServerOverloaded, RequestExpired, KeyError, ValueError,
                    TypeError) as e:
                answered.append(e)
                return None

        out = h.breaker.call(guarded)
        if answered:
            raise answered[0]
        return out

    def _mark_down(self, h: _Handle, state: str) -> None:
        with self._lock:
            h.ready = False
            h.state = state

    def _emit_failover(self, h: _Handle, trace_id: str,
                       exc: BaseException, kind: str = "score") -> None:
        self._failovers.inc()
        logger.warning("failover off %s (%s: %s)", h.name,
                       type(exc).__name__, exc)
        if events.recording_enabled():
            events.emit("fleet", "failover", replica=h.name,
                        trace_id=trace_id, kind=kind,
                        error=f"{type(exc).__name__}: {exc}")

    # -- Server-compatible surface (the HTTP front-end binds either) -------
    def submit_async(self, model: str, x,
                     deadline_ms: Optional[float] = None, *,
                     trace_id: Optional[str] = None):
        """Server-API shim for :func:`~mmlspark_tpu.serve.http.
        make_handler`: routes synchronously in the calling thread (HTTP
        connection threads already block on their reply) and returns a
        resolved Future carrying ``trace_id``."""
        from concurrent.futures import Future
        fut: Future = Future()
        tid = trace_id or _mint_trace_id()
        fut.trace_id = tid
        # routing errors propagate synchronously, matching Server's
        # submit_async admission semantics (the front-end maps them)
        fut.set_result(self.submit(model, x, deadline_ms, trace_id=tid))
        return fut

    def submit_many(self, model: str, x,
                    deadline_ms: Optional[float] = None,
                    timeout: Optional[float] = None) -> np.ndarray:
        arr = np.asarray(x)
        if arr.ndim == 1:
            arr = arr[None, :]
        bs = int(mmlconfig.get("serving.max_batch"))
        outs = [self.submit(model, arr[i:i + bs], deadline_ms)
                for i in range(0, arr.shape[0], bs)]
        return np.concatenate(outs, axis=0)

    @property
    def draining(self) -> bool:
        return all(h.state == "draining" for h in self._handles.values())

    def health(self) -> Dict[str, object]:
        """Fleet-level health: live while ANY replica is live, ready
        while ANY replica is ready."""
        with self._lock:
            ready = any(h.ready for h in self._handles.values())
            states = {h.name: h.state for h in self._handles.values()}
        live = ready or any(s in ("draining", "unknown")
                            for s in states.values())
        state = "ready" if ready else (
            "draining" if live else "closed")
        return {"live": live, "ready": ready, "state": state,
                "replicas": states}

    @property
    def registry(self) -> "_FleetRegistryView":
        return _FleetRegistryView(self)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            per = {h.name: {"weight": h.weight, "ready": h.ready,
                            "state": h.state, "routed": h.routed.value,
                            "breaker": h.breaker.state}
                   for h in self._handles.values()}
        out = {"replicas": per,
               "failovers": self._failovers.value,
               "all_shed": self._all_shed.value,
               "tenants": self.fairness.stats()}
        if self.affinity is not None:
            out["affinity"] = self.affinity.stats()
        return out

    def close(self) -> None:
        self.stop_prober()


class _FleetRegistryView:
    """Just enough registry surface for the HTTP front-end (`/models`):
    the first answering replica's model list (replicas serve the same
    set; during a rollout versions may transiently differ per replica)."""

    def __init__(self, router: Router):
        self._router = router

    def names(self) -> List[str]:
        for h in self._router._handles.values():
            try:
                return sorted(h.replica.models())
            except Exception:
                continue
        return []


class HttpReplica:
    """A remote serving process (``mmlspark-tpu serve``) behind the
    Replica protocol: scores over ``POST /score``, health over
    ``GET /healthz``. Transport failures raise
    :class:`ReplicaUnavailable`; HTTP status mapping mirrors the
    front-end's (503 -> :class:`ServerOverloaded` with the parsed
    ``Retry-After``, 504 -> :class:`RequestExpired`, 400 ->
    ``ValueError``)."""

    def __init__(self, addr: str, name: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 capacity_rows: int = 256):
        self.addr = addr.rstrip("/")
        if "://" not in self.addr:
            self.addr = "http://" + self.addr
        self.name = name or addr
        self.timeout_s = float(
            timeout_s if timeout_s is not None
            else mmlconfig.get("reliability.http_timeout"))
        self.capacity_rows = int(capacity_rows)

    def submit(self, model: str, x, deadline_ms: Optional[float] = None,
               trace_id: str = "") -> np.ndarray:
        import json as _json
        import urllib.error
        import urllib.request
        body = {"model": model, "x": np.asarray(x).tolist()}
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        if trace_id:
            body["trace_id"] = trace_id
        req = urllib.request.Request(
            f"{self.addr}/score", data=_json.dumps(body).encode("utf-8"),
            headers={"Content-Type": "application/json"})
        timeout = self.timeout_s
        if deadline_ms is not None:
            timeout = min(timeout, max(deadline_ms / 1e3, 0.001) + 1.0)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = _json.loads(resp.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            detail = self._error_detail(e)
            if e.code == 503:
                from mmlspark_tpu.models.downloader import _parse_retry_after
                raise ServerOverloaded(
                    f"replica {self.name} shed: {detail}",
                    retry_after=_parse_retry_after(
                        e.headers.get("Retry-After"))) from None
            if e.code == 504:
                raise RequestExpired(
                    f"replica {self.name}: {detail}") from None
            if e.code == 400:
                raise ValueError(
                    f"replica {self.name}: {detail}") from None
            raise ReplicaUnavailable(
                f"replica {self.name} HTTP {e.code}: {detail}") from None
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable: {e}") from None
        return np.asarray(payload["y"], np.float32)

    @staticmethod
    def _error_detail(e) -> str:
        import json as _json
        try:
            return str(_json.loads(e.read().decode("utf-8")).get(
                "error", ""))
        except Exception:
            return str(e)

    def health(self) -> Dict[str, object]:
        import json as _json
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"{self.addr}/healthz", timeout=self.timeout_s) as resp:
                body = _json.loads(resp.read().decode("utf-8"))
        except (urllib.error.URLError, OSError, ValueError,
                TimeoutError) as e:
            logger.debug("healthz %s unreachable: %s", self.name, e)
            return {"live": False, "ready": False, "state": "dead"}
        # pre-split servers answered {"status": "ok"|"draining"} only
        state = str(body.get("state")
                    or ("ready" if body.get("status") == "ok"
                        else body.get("status", "dead")))
        return {"live": bool(body.get("live", state != "closed")),
                "ready": bool(body.get("ready", state == "ready")),
                "state": state}

    def models(self) -> List[str]:
        import json as _json
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"{self.addr}/models", timeout=self.timeout_s) as resp:
                return list(
                    _json.loads(resp.read().decode("utf-8"))["models"])
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable: {e}") from None

    def _probe(self, endpoint: str) -> bool:
        """GET a liveness-style endpoint with the replica timeout. 200 is
        True, a 503 answer is False (the endpoint's not-yet contract), and
        a transport failure — connection refused mid-restart, torn socket,
        timeout — raises retryable :class:`ReplicaUnavailable` instead of
        leaking a raw ``URLError`` into the prober thread."""
        import urllib.error
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"{self.addr}{endpoint}", timeout=self.timeout_s):
                return True
        except urllib.error.HTTPError as e:
            if e.code == 503:
                return False
            raise ReplicaUnavailable(
                f"replica {self.name} {endpoint} HTTP {e.code}") from None
        except (urllib.error.URLError, OSError, TimeoutError) as e:
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable on {endpoint}: {e}"
            ) from None

    def probe_livez(self) -> bool:
        """Remote ``/livez``: True iff the process answers 200."""
        return self._probe("/livez")

    def probe_readyz(self) -> bool:
        """Remote ``/readyz``: True iff the replica is admitting traffic
        (a draining or warming replica answers 503 -> False)."""
        return self._probe("/readyz")
