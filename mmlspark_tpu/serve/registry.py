"""Multi-model serving registry: warm params in HBM, one compile per bucket.

Each registered :class:`~mmlspark_tpu.models.jax_model.JaxModel` gets a
:class:`ModelEntry` that owns the serving-side compiled artifacts:

- the model's bound apply closure (params already device-resident), built
  through the same ``_cached_jit`` key ``transform`` uses, so serving and
  offline scoring share one program cache and one numerics path;
- one AOT-compiled executable per batch bucket
  (``jitted.lower(params, ShapeDtypeStruct).compile()``), created by the
  :meth:`ModelEntry._compile` hook — the seam the compile-discipline test
  wraps to count compilations. Scoring a request NEVER triggers a compile
  outside this hook.

Residency follows the ``runtime.device_cache_mb`` budget that already
governs :mod:`~mmlspark_tpu.models.residency` and DeviceEpochCache: the
summed param bytes of warm entries must fit, and touching a model bumps it
to most-recently-used while colder entries are evicted (compiled programs
and the jit cache dropped, so the param tree they pin becomes collectable).
An evicted model is NOT unregistered — the next request re-warms it, paying
its compile again. Size the budget so the steady-state working set stays
warm.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.observability import memory as devmem
from mmlspark_tpu.reliability.breaker import CircuitBreaker
from mmlspark_tpu.utils import config as mmlconfig

# size arithmetic lives in the HBM ledger (lint Rule 11); this alias keeps
# the registry's historical spelling working. Per-SHARD bytes: a model
# sharded over the tensor axis pins only its shard on each chip, and the
# LRU budget / fleet HBM view must see that, not the logical total.
_param_bytes = devmem.param_shard_bytes


class PlacementOverBudget(ValueError):
    """A ``replace`` target placement's per-shard bytes exceed the
    registry budget. Raised BEFORE the old entry is dropped, so the
    caller's running version keeps serving — a bad reshard target
    degrades to a no-op, not an eviction storm."""


class ModelEntry:
    """One served model: coercion spec, bound apply, per-bucket programs."""

    def __init__(self, name: str, model, version: str = "v1"):
        self.name = name
        self.model = model
        self.version = version
        self._spec = model._spec()
        self._apply = None
        self._compiled: Dict[Tuple, Callable] = {}
        self.compile_count = 0   # REAL compiles only (cache loads excluded)
        self.cache_hits = 0      # programs loaded from the persistent cache
        self.kv_arena_bytes = 0  # decode KV arena charged by the
                                 # generative lane (0 = no lane); counted
                                 # into resident_bytes so the LRU budget
                                 # sees params + arena as one tenant
        # per-model breaker: a model whose program keeps dying (OOM, bad
        # params after a hot-swap) fails FAST instead of burning executor
        # time per batch; other models on the same server keep serving
        self.breaker = CircuitBreaker(f"serve.{name}")

    # -- warm-up ----------------------------------------------------------
    def ensure_apply(self):
        """The model's bound apply, built lazily through the SAME
        ``_cached_jit`` key as ``JaxModel.transform`` — registering a model
        that was already used offline reuses its closure (and vice versa)."""
        if self._apply is None:
            m = self.model
            apply, _, _, _ = m._cached_jit(
                lambda: m._build_apply(),
                key=(m.architecture, repr(m.get("architectureArgs")),
                     m.outputNodeName, repr(m.get("devicePreprocess")),
                     repr(m.get("meshSpec")), m.get("computeDtype"),
                     ))
            self._apply = apply
        return self._apply

    def coerce(self, arr) -> np.ndarray:
        """Host-side input coercion, identical to the offline scoring path
        (same ``_coerce_batch``), so served results are bit-identical to
        ``transform`` of the same rows."""
        return self.model._coerce_batch(np.asarray(arr), self._spec)

    # -- compile discipline ------------------------------------------------
    def _compile(self, bucket: int, row_shape: Tuple[int, ...],
                 dtype) -> Callable[[np.ndarray], np.ndarray]:
        """Build the executable for one (bucket, row-shape, dtype) batch
        shape. THE compile seam: every serving-path compilation funnels
        through here exactly once per key — tests wrap this method to
        assert the at-most-one-compile-per-bucket discipline.

        Models AOT-compile through
        :func:`mmlspark_tpu.compile_cache.load_or_compile` — the sanctioned
        seam (lint Rule 9) that loads a verified serialized executable from
        ``runtime.compile_cache_dir`` when one exists and compiles (then
        persists) otherwise, so the cost is paid at a deterministic point
        (first request of a bucket, or an explicit warmup) AND survives
        restarts/rollouts. Mesh-bound models (sharded recommenders, tensor-
        parallel scorers) go through the same seam: the lowering picks up
        the params' NamedShardings, so the persisted executable is the
        partitioned program — a warm restart of a SHARDED server is zero
        XLA compiles too. Should a backend refuse to serialize a multi-
        device executable, the store is counted as a bypass and serving
        proceeds on the freshly compiled program."""
        from mmlspark_tpu import compile_cache
        apply = self.ensure_apply()
        jitted = getattr(apply, "_jitted", None)
        if jitted is None:
            return apply
        params = apply._params
        mesh = getattr(apply, "_mesh", None)
        if mesh is not None:
            # placement identity in the cache key: an elastic reshard
            # serves the same name+version under different placements
            # and their partitioned executables must not collide
            mesh_key = ",".join(f"{a}={int(s)}"
                                for a, s in mesh.shape.items()
                                if int(s) > 1)
            # trace-time sharding constraints inside apply may name mesh
            # axes bare — keep the mesh current while lowering
            with mesh:
                result = compile_cache.load_or_compile(
                    self.name, self.version, bucket, tuple(row_shape),
                    dtype, jitted, params, mesh_key=mesh_key)
        else:
            result = compile_cache.load_or_compile(
                self.name, self.version, bucket, tuple(row_shape), dtype,
                jitted, params)
        if result.hit:
            self.cache_hits += 1
        else:
            self.compile_count += 1
        compiled = result.program
        return lambda x: compiled(params, x)

    @staticmethod
    def _program_key(bucket: int, row_shape: Tuple[int, ...],
                     dtype) -> Tuple:
        """Canonical program identity: the PADDED batch shape plus the
        numpy-canonical dtype name. Two buckets (or two dtype spellings —
        ``"f4"`` vs ``np.float32`` vs ``dtype('float32')``) resolving to
        the same padded shape share ONE compiled program and one
        persistent-cache entry instead of compiling twice."""
        return ((int(bucket),) + tuple(int(d) for d in row_shape),
                np.dtype(dtype).name)

    def program_for(self, bucket: int,
                    x: np.ndarray) -> Callable[[np.ndarray], np.ndarray]:
        key = self._program_key(bucket, x.shape[1:], x.dtype)
        prog = self._compiled.get(key)
        if prog is None:
            prog = self._compile(bucket, x.shape[1:], x.dtype)
            self._compiled[key] = prog
        return prog

    def score(self, x: np.ndarray) -> np.ndarray:
        """Score one padded bucket-shaped batch -> host float32 rows.
        Runs through the per-model circuit breaker: repeated failures trip
        it open and subsequent batches for THIS model fail immediately
        (``CircuitOpen``, retryable) until the half-open probe succeeds."""
        return self.breaker.call(self._score, x)

    def _score(self, x: np.ndarray) -> np.ndarray:
        out = np.asarray(self.program_for(x.shape[0], x)(x))
        if out.ndim == 1:
            out = out[:, None]
        return np.asarray(out, np.float32)

    # -- residency ---------------------------------------------------------
    def resident_bytes(self) -> int:
        """HBM bytes this entry pins (0 when cold): params plus any
        generative-lane KV arena charged against it."""
        if self._apply is None:
            return self.kv_arena_bytes
        params = getattr(self._apply, "_params", None)
        return (_param_bytes(params) if params is not None else 0) \
            + self.kv_arena_bytes

    @property
    def warm(self) -> bool:
        return self._apply is not None

    def evict(self) -> None:
        """Drop compiled programs AND the model's jit cache so the param
        tree they capture becomes collectable (the closure in
        ``_jit_cache`` pins params; clearing only ``_compiled`` would free
        nothing)."""
        self._apply = None
        self._compiled.clear()
        self.model._jit_cache = None
        self.model._out_spec_cache = None


class ModelRegistry:
    """Name -> :class:`ModelEntry`, LRU-bounded by ``runtime.device_cache_mb``.

    Thread-safe for registration and lookup; entry warm-up and scoring are
    serialized by the server's single executor thread.
    """

    def __init__(self, budget_mb: Optional[float] = None):
        self._budget_mb = budget_mb
        self._entries: "OrderedDict[str, ModelEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.evictions = 0

    def budget_bytes(self) -> float:
        mb = self._budget_mb
        if mb is None:
            mb = float(mmlconfig.get("runtime.device_cache_mb"))
        return mb * 1e6

    def add(self, name: str, model, version: str = "v1") -> ModelEntry:
        with self._lock:
            if name in self._entries:
                raise ValueError(f"model {name!r} already registered")
            entry = ModelEntry(name, model, version=version)
            self._entries[name] = entry
            return entry

    def replace(self, name: str, model, version: str) -> ModelEntry:
        """Atomically swap the entry behind ``name`` (the rollout /
        reshard cutover): lookups from the swap onward get the new
        version; a batch already holding the OLD entry finishes on it
        (that request was admitted pre-cutover). The old entry is evicted
        so its compiled programs and params become collectable — "retire
        old" in the rollout sequence. Unknown names register fresh (a
        rollout may introduce a model).

        The swap is guarded by a projected-bytes pre-check: a new
        placement whose PER-SHARD bytes cannot fit the budget raises
        :class:`PlacementOverBudget` BEFORE the old entry is touched —
        the running version keeps serving, instead of the old behaviour
        where the doomed replacement evicted every other warm model and
        then failed anyway."""
        projected = self.projected_bytes(model)
        budget = self.budget_bytes()
        if projected > budget:
            raise PlacementOverBudget(
                f"model {name!r} replacement rejected: projected per-shard "
                f"bytes {int(projected)} exceed the registry budget "
                f"{int(budget)} (runtime.device_cache_mb); the current "
                "entry keeps serving")
        with self._lock:
            old = self._entries.pop(name, None)
            entry = ModelEntry(name, model, version=version)
            self._entries[name] = entry
        if old is not None and old.warm:
            old.evict()
        return entry

    @staticmethod
    def projected_bytes(model) -> int:
        """Per-shard bytes ``model`` would pin once warmed, from host
        shapes + its ``meshSpec`` placement alone (nothing device-side;
        ledger arithmetic, lint Rule 11). 0 for models that carry no
        param state (stub scorers in tests)."""
        params = (getattr(model, "_state", None) or {}).get("params")
        if params is None:
            return 0
        resolve = getattr(model, "_resolve_score_mesh", None)
        mesh = resolve() if callable(resolve) else None
        return devmem.projected_shard_bytes(params, mesh)

    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(
                    f"unknown model {name!r}; registered: {self.names()}")
            self._entries.move_to_end(name)   # MRU
            return entry

    def names(self) -> List[str]:
        return sorted(self._entries)

    def touch(self, entry: ModelEntry) -> None:
        """After warming ``entry``, evict LRU entries until the warm set
        fits the budget. ``entry`` itself is exempt — a single over-budget
        model still serves (matching residency's force semantics), it just
        evicts everyone else."""
        evicted: List[Tuple[str, int]] = []
        with self._lock:
            budget = self.budget_bytes()
            while self._resident() > budget:
                victim = next(
                    (e for e in self._entries.values()
                     if e.warm and e is not entry), None)
                if victim is None:
                    break
                freed = victim.resident_bytes()
                victim.evict()
                self.evictions += 1
                evicted.append((victim.name, freed))
            resident = self._resident()
            warm = [(e.name, e._apply, e.kv_arena_bytes)
                    for e in self._entries.values()]
        ledger = devmem.get_ledger()
        for name, freed in evicted:
            ledger.on_eviction(name, freed, resident_bytes=resident,
                               budget_bytes=budget)
        # mirror the warm set into the ledger so the fleet view's
        # {model, kind} bytes always match the registry's own accounting;
        # embedding-table rows split out as kind="table" so the HBM panel
        # shows the business-scaling component apart from dense weights
        for name, apply, kv in warm:
            params = getattr(apply, "_params", None) if apply is not None \
                else None
            dense, table = devmem.split_param_shard_bytes(params)
            ledger.set_bytes(name, "params", dense)
            ledger.set_bytes(name, "table", table)
            ledger.set_bytes(name, "kv", kv)

    def _resident(self) -> int:
        return sum(e.resident_bytes() for e in self._entries.values())

    def resident_bytes(self) -> int:
        with self._lock:
            return self._resident()

    def release(self) -> None:
        """Close-time teardown: evict every entry and clear its lines
        from the HBM ledger, so a closed server (a killed fleet replica,
        a drained rollout victim) leaves ZERO {model, kind} bytes behind
        — the ledger must reconcile to what is actually resident, and a
        dead replica's table shards are not. Surviving replicas that
        share the model name re-mirror their own bytes on their next
        ``touch``."""
        with self._lock:
            entries = list(self._entries.values())
        ledger = devmem.get_ledger()
        for e in entries:
            if e.warm:
                e.evict()
            ledger.clear(e.name)

    def versions(self) -> Dict[str, str]:
        """Name -> served version (the rollout observability surface)."""
        with self._lock:
            return {n: e.version for n, e in sorted(self._entries.items())}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "models": len(self._entries),
                "warm": sum(1 for e in self._entries.values() if e.warm),
                "resident_bytes": self._resident(),
                "evictions": self.evictions,
                "compiles": sum(e.compile_count
                                for e in self._entries.values()),
                "compile_cache_hits": sum(e.cache_hits
                                          for e in self._entries.values()),
            }
