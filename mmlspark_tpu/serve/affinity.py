"""Prefix-affinity fleet routing: make N replicas one KV cache.

PR 12's shared-prefix KV cache is per-replica: at fleet scale, identical
system prompts re-prefill on every replica the prefix-blind WRR lands
them on, and a failover restarts from the prompt on a cold survivor.
This module closes that gap WITHOUT moving any KV bytes:

- **Advertisement.** Each replica summarizes its resident prefix-block
  hash chains (the chained sha256 keys minted by
  :func:`~mmlspark_tpu.serve.kvcache.prefix_block_hashes`) into a
  bounded top-K digest — ``KVCacheManager.stats()['resident_chains']``,
  ``generate.advertise_top_k`` entries of ``(chain hash, depth, hashes,
  leases, hits, last_use)``. The digest rides the normal stats surface
  (in-process ``server.stats()``; ``GET /affinity`` next to
  ``/metrics`` over HTTP) and is pulled fleet-wide by the
  :class:`~mmlspark_tpu.observability.aggregate.FleetScraper` into one
  shared :class:`AffinityState`.
- **Scoring.** For each generate request the router hashes the prompt's
  block chain host-side (same ``(model, kv_dtype, block_tokens)`` seed
  the replicas advertise) and walks every READY replica's digest: a
  replica's score is the deepest common prefix between the prompt's
  chain and any advertised chain — the expected prefix-hit depth in
  blocks. The deepest replica wins; ties (and scores below
  ``fleet.affinity_min_depth``) fall back to the smooth-WRR spread.
- **Session affinity.** Multi-turn traffic carrying a ``session`` key is
  consistent-hashed onto the READY ring (``fleet.affinity_vnodes``
  virtual nodes per replica, seeded by ``fleet.affinity_seed``) so every
  turn of a conversation lands where its KV history already is, with
  minimal reshuffle when a replica joins or retires.
- **Safety overrides affinity, always.** Selection only ever happens
  among the router's safe candidate set (ready, positive weight, not
  breaker-open, not already tried by this request) — a cache hit is
  never worth routing to a down, draining, or shedding replica. On
  failover the dead replica is excluded and the survivors are
  RE-scored, so the restarted sequence lands on the warmest survivor.
- **Rollout pre-warm.** The hottest observed prompt prefixes are
  retained host-side (tokens, not KV) so ``Fleet.rollout`` can replay
  them through a canary's prefill path before it takes weight — a
  rollout no longer resets the fleet hit rate to zero.

This module is the ONE sanctioned home for consistent-hash and
digest-scoring arithmetic in the tree (lint Rule 18); callers route
through :class:`AffinityState` and never open-code ring or depth math
(escape: ``# lint: allow-affinity``).
"""
from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mmlspark_tpu.observability import events, metrics
from mmlspark_tpu.serve.kvcache import prefix_block_hashes
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.affinity")


class PrefixDigest:
    """One replica's advertised resident-chain summary for one model.

    ``chains`` is the bounded top-K list straight from
    ``KVCacheManager.resident_chains()``; ``kv_dtype``/``block_tokens``
    are the hash-seed parameters a consumer needs to re-derive a
    prompt's chain with the same keys the replica minted."""

    __slots__ = ("replica", "model", "chains", "kv_dtype", "block_tokens",
                 "ts")

    def __init__(self, replica: str, model: str,
                 chains: Sequence[Dict[str, Any]], *,
                 kv_dtype: str = "", block_tokens: int = 0,
                 ts: float = 0.0):
        self.replica = str(replica)
        self.model = str(model)
        self.chains = [dict(c) for c in chains]
        self.kv_dtype = str(kv_dtype or "")
        self.block_tokens = int(block_tokens or 0)
        self.ts = float(ts)

    def max_depth(self) -> int:
        return max((int(c.get("depth", 0)) for c in self.chains), default=0)

    def to_dict(self) -> Dict[str, Any]:
        return {"replica": self.replica, "model": self.model,
                "chains": self.chains, "kv_dtype": self.kv_dtype,
                "block_tokens": self.block_tokens, "ts": self.ts}


def score_digest(digest: Optional[PrefixDigest],
                 prompt_hashes: Sequence[str]) -> int:
    """Expected prefix-hit depth (in blocks) of ``prompt_hashes`` on the
    replica behind ``digest``: the deepest common prefix between the
    prompt's chain and any advertised chain. Chained hashes make the
    walk exact — position i matches iff the ENTIRE prefix through block
    i is identical."""
    if digest is None or not prompt_hashes:
        return 0
    best = 0
    for c in digest.chains:
        depth = 0
        for adv, want in zip(c.get("hashes") or (), prompt_hashes):
            if adv != want:
                break
            depth += 1
        if depth > best:
            best = depth
    return best


class ConsistentHashRing:
    """Seeded consistent-hash ring over replica names.

    Each name contributes ``vnodes`` deterministic points (sha256 of
    ``seed|name|i``); a key lands on the first point clockwise of its
    own hash. Deterministic under seed, and stable under membership
    change: adding or retiring one replica only moves the keys whose
    nearest point belonged to it."""

    def __init__(self, names: Sequence[str], *,
                 vnodes: Optional[int] = None,
                 seed: Optional[int] = None):
        self.vnodes = int(vnodes if vnodes is not None
                          else mmlconfig.get("fleet.affinity_vnodes"))
        self.seed = int(seed if seed is not None
                        else mmlconfig.get("fleet.affinity_seed"))
        if self.vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {self.vnodes}")
        points: List[Tuple[int, str]] = []
        for name in sorted(set(names)):
            for i in range(self.vnodes):
                points.append((self._point(f"{self.seed}|{name}|{i}"),
                               name))
        points.sort()
        self._points = points
        self._keys = [p[0] for p in points]

    @staticmethod
    def _point(text: str) -> int:
        return int(hashlib.sha256(text.encode()).hexdigest()[:16], 16)

    def assign(self, key: str) -> Optional[str]:
        """The replica owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        h = self._point(f"k|{key}")
        i = bisect.bisect_right(self._keys, h)
        if i == len(self._points):
            i = 0                       # wrap past the top of the ring
        return self._points[i][1]


class AffinityHint:
    """Per-request routing context threaded from ``submit_generate``
    down to the pick: the prompt's chained block hashes (when the hash
    params are known from a digest) and the caller's session key."""

    __slots__ = ("model", "hashes", "session")

    def __init__(self, model: str, hashes: Optional[List[str]] = None,
                 session: Optional[str] = None):
        self.model = model
        self.hashes = hashes or []
        self.session = session


class _HotPrompt:
    """Heat-map entry for rollout pre-warm: the full-block token prefix
    behind one observed chain, with a hit count."""

    __slots__ = ("tokens", "hits")

    def __init__(self, tokens: List[int]):
        self.tokens = tokens
        self.hits = 0


class AffinityState:
    """Fleet-wide digest registry + routing scorer (thread-safe).

    One instance is shared between the :class:`~mmlspark_tpu.serve.
    router.Router` (which calls :meth:`select` per generate pick) and
    the :class:`~mmlspark_tpu.observability.aggregate.FleetScraper`
    (which calls :meth:`update_digest` per scrape). No KV bytes move:
    the state is hash chains and counters only."""

    def __init__(self, *, enabled: Optional[bool] = None,
                 min_depth: Optional[int] = None,
                 vnodes: Optional[int] = None,
                 seed: Optional[int] = None,
                 hot_prompts: int = 32):
        self.enabled = bool(mmlconfig.get("fleet.affinity_enabled")
                            if enabled is None else enabled)
        self.min_depth = int(mmlconfig.get("fleet.affinity_min_depth")
                             if min_depth is None else min_depth)
        self._vnodes = vnodes
        self._seed = seed
        self._lock = threading.Lock()
        # (replica, model) -> PrefixDigest
        self._digests: Dict[Tuple[str, str], PrefixDigest] = {}
        # model -> (kv_dtype, block_tokens) learned from advertisements
        self._hash_params: Dict[str, Tuple[str, int]] = {}
        # model -> {tail hash -> _HotPrompt} (bounded, for pre-warm)
        self._hot: Dict[str, Dict[str, _HotPrompt]] = {}
        self._hot_cap = int(hot_prompts)
        self._rings: Dict[Tuple[str, ...], ConsistentHashRing] = {}
        self.routes_prefix = 0
        self.routes_session = 0
        self.routes_wrr = 0
        self.spills = 0             # picks bounced off a loaded leader
        self.depth_hist: Dict[int, int] = {}

    # -- advertisement -----------------------------------------------------
    def update_digest(self, replica: str, model: str,
                      chains: Sequence[Dict[str, Any]], *,
                      kv_dtype: Any = None, block_tokens: Any = None,
                      ts: float = 0.0) -> None:
        """Publish one replica's scraped chain summary for ``model``."""
        d = PrefixDigest(replica, model, chains,
                         kv_dtype=str(kv_dtype or ""),
                         block_tokens=int(block_tokens or 0), ts=ts)
        with self._lock:
            self._digests[(d.replica, d.model)] = d
            if d.kv_dtype and d.block_tokens:
                self._hash_params[d.model] = (d.kv_dtype, d.block_tokens)
        if events.recording_enabled():
            events.emit("affinity", "advertise", replica=d.replica,
                        model=d.model, chains=len(d.chains),
                        max_depth=d.max_depth())
        if metrics.metrics_enabled():
            metrics.gauge(
                f"affinity.advertised_chains.{d.replica}").set(
                    float(len(d.chains)))

    def forget(self, replica: str) -> None:
        """Drop a retired replica's digests (its chains died with it)."""
        with self._lock:
            for key in [k for k in self._digests if k[0] == replica]:
                del self._digests[key]

    def digest_for(self, replica: str, model: str
                   ) -> Optional[PrefixDigest]:
        with self._lock:
            return self._digests.get((replica, model))

    # -- request-side hashing ----------------------------------------------
    def hint_for(self, model: str, prompt: Sequence[int],
                 session: Optional[str] = None
                 ) -> Optional[AffinityHint]:
        """Build the routing hint for one generate request: hash the
        prompt's block chain host-side with the SAME seed the replicas
        advertise. Before any digest has arrived (cold fleet, scraper
        not running) the hash params are unknown — the hint then
        carries only the session key, and routing is pure WRR."""
        if not self.enabled:
            return None
        with self._lock:
            params = self._hash_params.get(model)
        hashes: List[str] = []
        if params is not None:
            kv_dtype, bt = params
            hashes = prefix_block_hashes(model, kv_dtype, prompt, bt)
            if hashes:
                self._observe_prompt(model, hashes, list(prompt), bt)
        if not hashes and not session:
            return None
        return AffinityHint(model, hashes, session)

    def _observe_prompt(self, model: str, hashes: List[str],
                        prompt: List[int], block_tokens: int) -> None:
        """Track the hottest full-block prompt prefixes (tokens, host
        RAM only) so a rollout canary can replay them through prefill."""
        tail = hashes[-1]
        tokens = prompt[:len(hashes) * block_tokens]
        with self._lock:
            heat = self._hot.setdefault(model, {})
            hp = heat.get(tail)
            if hp is None:
                if len(heat) >= self._hot_cap:
                    # LFU: the coldest entry makes room (hot chains have
                    # accumulated hits and survive one-off prompts)
                    del heat[min(heat, key=lambda k: heat[k].hits)]
                hp = heat[tail] = _HotPrompt(tokens)
            hp.hits += 1

    def hot_prompts(self, model: str, limit: int) -> List[List[int]]:
        """The ``limit`` hottest full-block prompt prefixes observed for
        ``model``, hottest first — the rollout pre-warm replay set."""
        if limit <= 0:
            return []
        with self._lock:
            heat = self._hot.get(model, {})
            ranked = sorted(heat.values(), key=lambda hp: -hp.hits)
            return [list(hp.tokens) for hp in ranked[:int(limit)]]

    # -- selection ---------------------------------------------------------
    def select(self, candidates: Sequence[str], hint: AffinityHint
               ) -> Tuple[List[str], str, int]:
        """Narrow the router's SAFE candidate set for one pick.

        Returns ``(names, mode, depth)``: the (sub)set to run the
        smooth-WRR spread over, how it was chosen (``session`` /
        ``prefix`` / ``wrr``), and the expected hit depth in blocks.
        ``candidates`` has already been filtered to ready, positive-
        weight, non-excluded replicas — affinity only ever reorders
        WITHIN that set, so a breaker-open, draining, shedding, or
        already-tried replica is never chosen to chase a cache hit."""
        names = list(candidates)
        if not self.enabled or not names:
            return names, "wrr", 0
        if hint.session:
            ring_key = tuple(sorted(names))
            with self._lock:
                ring = self._rings.get(ring_key)
                if ring is None:
                    ring = ConsistentHashRing(
                        names, vnodes=self._vnodes, seed=self._seed)
                    if len(self._rings) > 64:   # membership-churn bound
                        self._rings.clear()
                    self._rings[ring_key] = ring
            owner = ring.assign(hint.session)
            if owner is not None:
                depth = 0
                if hint.hashes:
                    depth = score_digest(
                        self.digest_for(owner, hint.model), hint.hashes)
                return [owner], "session", depth
        if hint.hashes:
            scores = {n: score_digest(self.digest_for(n, hint.model),
                                      hint.hashes) for n in names}
            best = max(scores.values())
            if best >= max(1, self.min_depth):
                leaders = [n for n in names if scores[n] == best]
                return leaders, "prefix", best
        return names, "wrr", 0

    # -- accounting --------------------------------------------------------
    def observe_route(self, replica: str, mode: str, depth: int) -> None:
        """Count one routed generate request (the affinity-vs-WRR split
        and the fleet hit-depth histogram in reports/top)."""
        with self._lock:
            if mode == "prefix":
                self.routes_prefix += 1
            elif mode == "session":
                self.routes_session += 1
            else:
                self.routes_wrr += 1
            d = int(depth)
            self.depth_hist[d] = self.depth_hist.get(d, 0) + 1
        if events.recording_enabled():
            events.emit("affinity", "route", replica=replica, mode=mode,
                        depth=int(depth))

    def observe_spill(self) -> None:
        """Count one bounded-load spill: affinity had a leader but every
        copy of it was over the in-flight cap, so the pick fell back to
        WRR (the route itself is then counted as a WRR route)."""
        with self._lock:
            self.spills += 1
        if events.recording_enabled():
            events.emit("affinity", "spill")

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = (self.routes_prefix + self.routes_session
                     + self.routes_wrr)
            return {
                "enabled": self.enabled,
                "routes": total,
                "routes_prefix": self.routes_prefix,
                "routes_session": self.routes_session,
                "routes_wrr": self.routes_wrr,
                "affinity_route_share": round(
                    (self.routes_prefix + self.routes_session)
                    / total, 4) if total else 0.0,
                "spills": self.spills,
                "depth_hist": dict(sorted(self.depth_hist.items())),
                "digests": len(self._digests),
            }

    def snapshot(self) -> Dict[str, Any]:
        """The scraper/dashboard view: routing split + per-replica
        advertised chains."""
        out = self.stats()
        with self._lock:
            out["advertised"] = [
                {"replica": d.replica, "model": d.model,
                 "chains": len(d.chains), "max_depth": d.max_depth(),
                 "leases": sum(int(c.get("leases", 0))
                               for c in d.chains)}
                for d in sorted(self._digests.values(),
                                key=lambda d: (d.replica, d.model))]
        return out
