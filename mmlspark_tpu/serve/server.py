"""In-process inference server: admission -> micro-batch -> score -> respond.

The request-level front half the ROADMAP's "serves heavy traffic" north
star needs and the reference never had (``CNTKModel`` scored whole
DataFrames; a request had to wait for a batch job). The shape:

- **Admission** (caller threads): inputs are coerced through the model's
  own ``_coerce_batch`` (so served numerics are bit-identical to offline
  ``transform``), wrapped in a :class:`~mmlspark_tpu.serve.batcher.Ticket`
  and pushed into a BOUNDED queue. A full queue rejects immediately with
  :class:`ServerOverloaded` (``retryable = True`` — ``RetryPolicy``'s
  default classifier backs off and retries it) instead of growing latency
  unboundedly: shed early, shed cheap.
- **One executor thread** owns the device: it drains the queue into a
  :class:`~mmlspark_tpu.serve.batcher.MicroBatcher`, flushes on
  ``max_batch``/``max_wait_ms``, cancels tickets whose deadline passed
  while queued (:class:`RequestExpired` — never scored, the work is
  already worthless), pads the group to a compiled bucket, and scores it
  through the :class:`~mmlspark_tpu.serve.registry.ModelRegistry`. Single
  ownership means no device-side locking and a deterministic batch
  sequence for fault replay.
- **Telemetry**: admitted/shed/expired/completed counters are
  unconditional; queue-depth + batch-occupancy gauges and the
  queue/pad/compute latency histograms gate on ``metrics_enabled()``; one
  ``serving.request`` event per request (the report's p50/p99 source) and
  ``serving.shed``/``serving.expired`` events gate on the event log.
- **Fault sites** ``serve.enqueue`` / ``serve.batch`` / ``serve.score``
  let a FaultPlan replay overload and mid-batch-crash scenarios
  deterministically (a ``serve.score`` raise fails that batch's futures
  and the executor keeps serving — the blast radius of a bad batch is
  that batch).
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.observability import events, metrics, spans
from mmlspark_tpu.reliability import watchdog as _watchdog
from mmlspark_tpu.reliability.faults import fault_site
from mmlspark_tpu.serve.batcher import (
    MicroBatcher, Ticket, bucket_for, default_buckets, parse_buckets,
)
from mmlspark_tpu.serve.registry import ModelRegistry
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve")

_STOP = object()

# request trace ids: per-process counter + pid, so merged multi-replica
# logs never collide and an id is greppable end to end (shed/expired/
# request events, tail-sampled spans, histogram exemplars, HTTP response)
_trace_ids = itertools.count(1)
_trace_lock = threading.Lock()


def _mint_trace_id() -> str:
    with _trace_lock:
        n = next(_trace_ids)
    return f"t-{os.getpid():x}-{n:x}"


class ServeError(RuntimeError):
    """Base for serving-path failures."""


class ServerOverloaded(ServeError):
    """Admission rejected: the bounded queue is full. Retryable by
    contract — ``reliability.retry.default_retryable`` reads this class
    attribute, so a client wrapping ``submit`` in ``RetryPolicy`` backs
    off and retries without custom classification.

    ``retry_after`` (seconds, or None) is the server's backoff ask: the
    HTTP front-end maps it to the ``Retry-After`` header, the retry layer
    reads it through the ``retry_after`` attribute protocol, and the
    fleet router consolidates the MINIMUM across replicas when every
    replica sheds (come back when the soonest one frees up)."""
    retryable = True

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg)
        self.retry_after = retry_after


class RequestExpired(ServeError):
    """The request's deadline passed before scoring started; it was
    cancelled at dequeue, not computed. NOT retryable by default — the
    caller's deadline already elapsed, retrying is their call."""


class ServerClosed(ServeError):
    """Submitted to a server after ``close()``."""


class _Twin:
    """A per-instance counter that also feeds the process-wide metric of
    the same name: ``value`` is THIS server's count (stats()/inflight for
    one fleet replica), the registry counter stays the process aggregate
    the exposition endpoint and existing dashboards read."""

    __slots__ = ("_local", "_global")

    def __init__(self, name: str):
        self._local = metrics.Counter(name)
        self._global = metrics.counter(name)

    def inc(self, n: float = 1.0) -> None:
        self._local.inc(n)
        self._global.inc(n)

    @property
    def value(self) -> float:
        return self._local.value


class Server:
    """Dynamic micro-batching inference server over a model registry.

    ``models`` maps serving names to fitted
    :class:`~mmlspark_tpu.models.jax_model.JaxModel`-like stages (anything
    with ``_spec``/``_coerce_batch``/``_build_apply``). Knobs default from
    the ``serving.*`` config namespace. ``start=False`` leaves the
    executor unstarted — tests drive admission and ``_flush`` directly
    for deterministic overload/expiry coverage.
    """

    def __init__(self, models: Dict[str, object], *,
                 max_batch: Optional[int] = None,
                 max_wait_ms: Optional[float] = None,
                 queue_depth: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None,
                 clock=None, start: bool = True):
        self.max_batch = int(max_batch if max_batch is not None
                             else mmlconfig.get("serving.max_batch"))
        wait_ms = float(max_wait_ms if max_wait_ms is not None
                        else mmlconfig.get("serving.max_wait_ms"))
        self.max_wait_s = wait_ms / 1e3
        depth = int(queue_depth if queue_depth is not None
                    else mmlconfig.get("serving.queue_depth"))
        if buckets is None:
            text = str(mmlconfig.get("serving.buckets"))
            self.buckets = parse_buckets(text, self.max_batch) if text \
                else default_buckets(self.max_batch)
        else:
            self.buckets = tuple(sorted(set(int(b) for b in buckets)))
            if self.buckets[-1] < self.max_batch:
                raise ValueError(
                    f"largest bucket {self.buckets[-1]} < max_batch "
                    f"{self.max_batch}")
        self.clock = clock if clock is not None else events.perf
        self.registry = ModelRegistry()
        for name, model in models.items():
            self.registry.add(name, model)
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._batcher = MicroBatcher(self.max_batch, self.max_wait_s,
                                     clock=self.clock)
        # _admit serializes the admission-state check against the enqueue
        # AND against close()/drain() flipping that state: without it a
        # ticket could pass the check, lose the CPU, and be enqueued after
        # the executor drained — a future nobody will ever resolve.
        self._admit = threading.Lock()
        self._closed = False
        self._draining = False
        self._thread: Optional[threading.Thread] = None
        # counters are unconditional (lock + int add); gauges/histograms
        # gate per-use on metrics_enabled(). The metrics registry is
        # process-wide — with N in-process fleet replicas those counters
        # aggregate — so per-instance Counter twins back stats()/inflight.
        self._admitted = _Twin("serving.admitted")
        self._shed = _Twin("serving.shed")
        self._expired = _Twin("serving.expired")
        self._completed = _Twin("serving.completed")
        self._failed = _Twin("serving.failed")
        # per-instance latency histogram: the process-wide
        # serving.total_ms aggregates across in-process fleet replicas,
        # but the fleet scraper and stats() need THIS replica's p50/p99
        self._latency = metrics.Histogram("serving.total_ms")
        # generative lanes (serve/generate.py), one per decoder-LM model,
        # created lazily on the first submit_generate
        self._lanes: Dict[str, object] = {}
        self._autostart = start
        if start:
            self.start()

    @staticmethod
    def _twin(name: str) -> _Twin:
        """Per-instance + process-global counter pair (the generative lane
        counts through the same twin scheme as the scoring path)."""
        return _Twin(name)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="mmlspark-tpu-serve", daemon=True)
        self._thread.start()

    def close(self, drain: bool = True,
              timeout_s: Optional[float] = None) -> None:
        """Stop the executor. ``drain=True`` scores everything already
        admitted first; ``drain=False`` fails pending work with a
        retryable :class:`ServerOverloaded` (shed to another replica, not
        a hang). Idempotent and race-safe: the second call is a no-op,
        and the admission lock guarantees no ticket slips into the queue
        after the executor stops — every admitted future resolves.
        ``timeout_s`` bounds the executor join (default
        ``serving.drain_timeout_s``)."""
        with self._admit:
            if self._closed:
                return
            self._closed = True
            self._draining = True
        if timeout_s is None:
            timeout_s = float(mmlconfig.get("serving.drain_timeout_s"))
        for lane in list(self._lanes.values()):
            lane.close(timeout_s=timeout_s)
        if self._thread is not None:
            self._queue.put(_STOP)
            self._thread.join(timeout=max(timeout_s, 0.1))
            if self._thread.is_alive():
                logger.warning("serve executor did not stop within %.1fs",
                               timeout_s)
            self._thread = None
        leftovers = [t for t in self._drain_tickets() if t is not _STOP]
        if drain:
            for t in leftovers:
                self._batcher.offer(t)
            while len(self._batcher):
                self._flush()
        else:
            while len(self._batcher):
                leftovers.extend(self._batcher.take())
            for t in leftovers:
                if not t.future.done():
                    self._failed.inc()
                    t.future.set_exception(ServerOverloaded(
                        "server closed before scoring; retry elsewhere",
                        retry_after=1.0))
        # the ledger reconciles on close: a dead replica's param/table/kv
        # lines must not linger in the fleet HBM view
        self.registry.release()
        if events.events_enabled():
            s = self.stats()
            events.emit("serving", "summary", **s)

    def drain(self, timeout_s: Optional[float] = None,
              reason: str = "drain") -> None:
        """Graceful shutdown for preemption: stop admission FIRST (new
        submits shed with retryable :class:`ServerOverloaded`, the HTTP
        front-end maps that to 503 + ``Retry-After``), finish everything
        already admitted, then close. ``timeout_s`` defaults to
        ``serving.drain_timeout_s``. Idempotent."""
        with self._admit:
            if self._closed:
                return
            already = self._draining
            self._draining = True
        if not already:
            logger.warning("serve: draining (%s); admission stopped", reason)
            metrics.counter("serving.drains").inc()
            if events.events_enabled():
                events.emit("event", "preemption.drain", kind="serve",
                            reason=reason,
                            pending=self._queue.qsize() +
                            len(self._batcher))
        self.close(drain=True, timeout_s=timeout_s)

    @property
    def draining(self) -> bool:
        return self._draining and not self._closed

    def health(self) -> Dict[str, object]:
        """Liveness vs readiness, split (the k8s-probe distinction the
        fleet router routes on): a DRAINING server is still ``live`` —
        in-flight work finishes, ``/healthz`` answers — but no longer
        ``ready`` for new traffic, so the router rotates it out BEFORE it
        stops being alive. ``state`` is one of ``ready``/``draining``/
        ``closed``."""
        if self._closed:
            state = "closed"
        elif self._draining:
            state = "draining"
        else:
            state = "ready"
        return {"live": not self._closed, "ready": state == "ready",
                "state": state}

    @property
    def inflight(self) -> int:
        """Requests admitted but not yet resolved (completed, expired, or
        failed) — the rollout drain condition."""
        n = self._admitted.value - self._completed.value \
            - self._expired.value - self._failed.value
        return max(0, int(round(n)))

    @property
    def capacity_rows(self) -> int:
        """Admission headroom (the bounded-queue depth, i.e. in-flight
        requests this replica holds before shedding): the fleet fairness
        layer sizes tenant shares from the sum of replica capacities."""
        return self._queue.maxsize

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- submission (caller threads) --------------------------------------
    def submit_async(self, model: str, x,
                     deadline_ms: Optional[float] = None, *,
                     trace_id: Optional[str] = None) -> Future:
        """Admit one request (a single example or a small batch of rows up
        to ``max_batch``); returns a Future resolving to the scored rows
        (float32, one row per input row). Raises :class:`ServerOverloaded`
        synchronously when the queue is full. ``trace_id`` lets a fleet
        router thread ONE id through a failover chain — when None the
        server mints its own."""
        if self._closed:
            raise ServerClosed("server closed")
        if self._draining:
            raise ServerOverloaded("server draining; retry elsewhere",
                                   retry_after=1.0)
        entry = self.registry.get(model)   # KeyError surfaces here, early
        arr = np.asarray(x)
        if arr.ndim == 1:
            arr = arr[None, :]
        coerced = entry.coerce(arr)
        if coerced.shape[0] > self.max_batch:
            raise ValueError(
                f"{coerced.shape[0]} rows > max_batch {self.max_batch}; "
                "use submit_many for large arrays")
        now = self.clock()
        if deadline_ms is None:
            dms = float(mmlconfig.get("serving.default_deadline_ms"))
            deadline_ms = dms if dms > 0 else None
        deadline = now + deadline_ms / 1e3 if deadline_ms else None
        ticket = Ticket(model, coerced, coerced.shape[0], Future(),
                        enqueued=now, deadline=deadline,
                        trace_id=trace_id or _mint_trace_id())
        # callers (the HTTP front-end) read the id off the future they
        # already hold — no parallel return channel needed
        ticket.future.trace_id = ticket.trace_id
        fault_site("serve.enqueue", {"model": model,
                                     "rows": ticket.rows})
        try:
            # check-and-enqueue is atomic against close()/drain(): a
            # ticket is either in the queue BEFORE the stop sentinel (the
            # executor or close() resolves it) or rejected here — never
            # admitted into a stopped server.
            with self._admit:
                if self._closed:
                    raise ServerClosed("server closed")
                if self._draining:
                    raise ServerOverloaded(
                        "server draining; retry elsewhere",
                        retry_after=1.0)
                self._queue.put_nowait(ticket)
        except queue.Full:
            self._shed.inc()
            if events.recording_enabled():
                events.emit("serving", "shed", model=model,
                            rows=ticket.rows, trace_id=ticket.trace_id)
            raise ServerOverloaded(
                f"queue full ({self._queue.maxsize} pending); retry with "
                "backoff",
                retry_after=float(
                    mmlconfig.get("serving.retry_after_s"))) from None
        self._admitted.inc()
        if metrics.metrics_enabled():
            metrics.gauge("serving.queue_depth").set(self._queue.qsize())
        return ticket.future

    def submit(self, model: str, x,
               deadline_ms: Optional[float] = None,
               timeout: Optional[float] = None) -> np.ndarray:
        """Blocking :meth:`submit_async`."""
        return self.submit_async(model, x, deadline_ms).result(timeout)

    # -- generative lane ---------------------------------------------------
    def enable_generate(self, model: str, *, clock=None,
                        start: Optional[bool] = None):
        """Create (or return) the generative lane for ``model`` — its own
        executor thread, KV arena, and bucketed prefill/decode programs
        (see :mod:`~mmlspark_tpu.serve.generate`). Lazy: plain scoring
        servers never pay for an arena. ``start=False`` leaves the lane
        thread unstarted for test-driven stepping."""
        from mmlspark_tpu.serve.generate import GenerateLane
        with self._admit:
            if self._closed:
                raise ServerClosed("server closed")
            lane = self._lanes.get(model)
            if lane is None:
                lane = GenerateLane(
                    self, model, clock=clock,
                    start=self._autostart if start is None else start)
                self._lanes[model] = lane
        return lane

    def reset_lane(self, model: str,
                   timeout_s: Optional[float] = None) -> bool:
        """Close and forget the generate lane for ``model`` (False when
        it has none). The reshard seam: a lane's KV arena and bucketed
        prefill/decode programs are bound to the placement of the entry
        it was built against, so after a ``registry.replace`` onto a new
        mesh the old lane must die — the next ``submit_generate`` (or an
        explicit ``enable_generate``) builds a fresh lane against the
        CURRENT entry, arena re-sharded onto the new placement. Closing
        fails unfinished sequences with a retryable error; the fleet
        router failover-restarts them from their prompts, token-
        identically under seeded sampling."""
        with self._admit:
            lane = self._lanes.pop(model, None)
        if lane is None:
            return False
        lane.close(timeout_s=timeout_s)
        return True

    def submit_generate(self, model: str, prompt,
                        max_new_tokens: Optional[int] = None, *,
                        temperature: float = 0.0, top_k: int = 0,
                        seed: int = 0, eos_id: Optional[int] = None,
                        deadline_ms: Optional[float] = None,
                        trace_id: Optional[str] = None) -> Future:
        """Admit one generation request; the Future resolves to a dict
        with ``tokens`` (sampled ids), ``finish_reason``, ``ttft_ms`` and
        ``trace_id``. Sheds with retryable :class:`ServerOverloaded` when
        the KV arena cannot hold the sequence's full block budget."""
        from mmlspark_tpu.serve.generate import GenerateRequest
        if self._closed:
            raise ServerClosed("server closed")
        if self._draining:
            raise ServerOverloaded("server draining; retry elsewhere",
                                   retry_after=1.0)
        self.registry.get(model)   # KeyError surfaces here, early
        if max_new_tokens is None:
            max_new_tokens = int(mmlconfig.get("generate.max_new_tokens"))
        lane = self.enable_generate(model)
        return lane.submit(GenerateRequest(
            model=model, prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, seed=seed,
            eos_id=eos_id, deadline_ms=deadline_ms,
            trace_id=trace_id or ""))

    def generate(self, model: str, prompt,
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None, **kw) -> Dict:
        """Blocking :meth:`submit_generate`."""
        return self.submit_generate(model, prompt, max_new_tokens,
                                    **kw).result(timeout)

    def submit_many(self, model: str, x,
                    deadline_ms: Optional[float] = None,
                    timeout: Optional[float] = None) -> np.ndarray:
        """Score a large array by splitting it into ``max_batch``-row
        requests admitted back-to-back (they coalesce into full batches),
        then reassembling in order."""
        arr = np.asarray(x)
        if arr.ndim == 1:
            arr = arr[None, :]
        futures = [self.submit_async(model, arr[i:i + self.max_batch],
                                     deadline_ms)
                   for i in range(0, arr.shape[0], self.max_batch)]
        return np.concatenate([f.result(timeout) for f in futures], axis=0)

    def pump(self, max_batches: Optional[int] = None) -> int:
        """Synchronously run the executor's coalesce+flush step on the
        CALLER's thread: drain every queued ticket into the batcher, then
        flush up to ``max_batches`` groups (all of them when ``None``).
        Returns the number of groups flushed.

        This is the deterministic drive for ``start=False`` servers — the
        autopilot chaos scenario and bench lane step whole fleets in
        virtual time with it, one pump per replica per tick, so the
        request schedule is a pure function of the seed. Unscored backlog
        stays in the BOUNDED queue (only the rows each flushed group can
        take are drained), so ``queue_depth`` remains an honest
        backpressure signal between pumps — the signal the autopilot's
        scale lever reads. Calling it on a started server is unsupported
        (two executors would race for the same batcher)."""
        if self._closed:
            raise ServerClosed("server closed")
        done = 0
        while max_batches is None or done < max_batches:
            rows = 0
            while rows < self.max_batch:
                try:
                    t = self._queue.get_nowait()
                except queue.Empty:
                    break
                if t is _STOP:
                    continue
                self._batcher.offer(t)
                rows += t.rows
            if not len(self._batcher):
                break
            self._flush()
            done += 1
        if metrics.metrics_enabled():
            metrics.gauge("serving.queue_depth").set(self._queue.qsize())
        return done

    # -- executor ----------------------------------------------------------
    def _run(self) -> None:
        # liveness: the executor beats once per loop pass; the idle wait
        # is bounded (never a blocking get(None)) so an EMPTY server still
        # beats and only a wedged flush reads as a stall
        hb = _watchdog.register("serve.executor")
        try:
            self._run_loop(hb)
        finally:
            hb.close()

    def _run_loop(self, hb) -> None:
        stopping = False
        while True:
            hb.beat()
            wait = self._batcher.wait_s()
            wait = 0.5 if wait is None else min(wait, 0.5)
            try:
                item = self._queue.get(timeout=wait)
            except queue.Empty:
                item = None          # deadline flush fires below
            if item is _STOP:
                stopping = True
            elif item is not None:
                self._batcher.offer(item)
            # opportunistic drain: everything already queued joins this
            # coalescing round without further blocking
            for t in self._drain_tickets():
                if t is _STOP:      # pragma: no cover - close() races
                    stopping = True
                else:
                    self._batcher.offer(t)
            if metrics.metrics_enabled():
                metrics.gauge("serving.queue_depth").set(self._queue.qsize())
            while self._batcher.ready() \
                    or (stopping and len(self._batcher)):
                self._flush()
            if stopping:
                return

    def _drain_tickets(self) -> List:
        out: List = []
        while True:
            try:
                out.append(self._queue.get_nowait())
            except queue.Empty:
                return out

    def _flush(self) -> None:
        """Dequeue one head group, cancel expired tickets, pad to a
        bucket, score, resolve futures. Any failure fails THIS group's
        futures and leaves the executor serving."""
        t_dequeue = self.clock()
        group = self._batcher.take()
        live: List[Ticket] = []
        for t in group:
            if t.expired(t_dequeue):
                self._expired.inc()
                if events.recording_enabled():
                    events.emit("serving", "expired", model=t.model,
                                rows=t.rows, trace_id=t.trace_id,
                                waited_ms=round(
                                    (t_dequeue - t.enqueued) * 1e3, 3))
                t.future.set_exception(RequestExpired(
                    f"deadline passed {t_dequeue - t.deadline:.3f}s before "
                    "scoring"))
            else:
                live.append(t)
        if not live:
            return
        try:
            rows = sum(t.rows for t in live)
            fault_site("serve.batch", {"model": live[0].model,
                                       "tickets": len(live), "rows": rows})
            bucket = bucket_for(rows, self.buckets)
            x = np.concatenate([t.x for t in live], axis=0) \
                if len(live) > 1 else live[0].x
            if rows < bucket:
                pad = np.zeros((bucket - rows,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            t_padded = self.clock()
            entry = self.registry.get(live[0].model)
            entry.ensure_apply()
            self.registry.touch(entry)
            fault_site("serve.score", {"model": entry.name,
                                       "bucket": bucket})
            out = entry.score(x)
            t_scored = self.clock()
            self._respond(live, out, bucket, rows,
                          t_dequeue, t_padded, t_scored)
        except Exception as e:  # fail the group, keep serving
            logger.error("serve batch failed: %s", e)
            for t in live:
                if not t.future.done():
                    self._failed.inc()
                    t.future.set_exception(e)

    def _respond(self, live: List[Ticket], out: np.ndarray, bucket: int,
                 rows: int, t_dequeue: float, t_padded: float,
                 t_scored: float) -> None:
        hot = metrics.metrics_enabled()
        log = events.recording_enabled()
        slow_ms = float(mmlconfig.get("observability.trace_slow_ms") or 0.0)
        pad_s = t_padded - t_dequeue
        compute_s = t_scored - t_padded
        if hot:
            metrics.gauge("serving.batch_occupancy").set(rows / bucket)
            metrics.histogram("serving.pad_ms").observe(pad_s * 1e3)
            metrics.histogram("serving.compute_ms").observe(compute_s * 1e3)
        offset = 0
        for t in live:
            res = out[offset:offset + t.rows]
            offset += t.rows
            queue_s = t_dequeue - t.enqueued
            total_s = t_scored - t.enqueued
            # tail sampling: only requests over the threshold pay for full
            # span detail; everyone else keeps the one cheap request event
            slow = slow_ms > 0 and total_s * 1e3 >= slow_ms
            self._completed.inc()
            if hot:
                ex = t.trace_id if slow else None
                metrics.histogram("serving.queue_ms").observe(
                    queue_s * 1e3, exemplar=ex)
                metrics.histogram("serving.total_ms").observe(
                    total_s * 1e3, exemplar=ex)
                self._latency.observe(total_s * 1e3, exemplar=ex)
            if log:
                events.emit("serving", "request", model=t.model,
                            rows=t.rows, bucket=bucket,
                            trace_id=t.trace_id, slow=slow,
                            occupancy=round(rows / bucket, 4),
                            queue_ms=round(queue_s * 1e3, 3),
                            pad_ms=round(pad_s * 1e3, 3),
                            compute_ms=round(compute_s * 1e3, 3),
                            total_ms=round(total_s * 1e3, 3))
                if slow:
                    self._emit_slow_trace(t, queue_s, pad_s, compute_s,
                                          total_s, bucket)
            t.future.set_result(res)

    def _emit_slow_trace(self, t: Ticket, queue_s: float, pad_s: float,
                         compute_s: float, total_s: float,
                         bucket: int) -> None:
        """Retroactively emit the span timeline of one slow request:
        a ``serve:request`` root with ``queue``/``pad``/``compute``
        children, every span carrying the ticket's ``trace_id``.

        Spans can only be emitted retroactively here — at enqueue time
        nobody knows the request will be slow; that is the point of tail
        sampling. Wall-clock starts are back-dated from ``events.wall()``
        by the executor-clock durations, so the exported trace nests these
        under the same timeline as live spans (and the back-dating works
        under the tests' injected clocks too)."""
        wall_end = events.wall()
        pid = os.getpid()
        root_id = spans.next_span_id()
        root_start = wall_end - total_s

        def emit(name: str, span_id: int, parent_id: Optional[int],
                 depth: int, start: float, dur: float, **attrs) -> None:
            events.emit(
                "span", name, span_id=span_id, pid=pid,
                parent_id=parent_id,
                parent="serve:request" if parent_id else "",
                depth=depth, start=round(start, 6), dur_s=round(dur, 9),
                attrs={"trace_id": t.trace_id, **attrs})

        emit("serve:request", root_id, None, 0, root_start, total_s,
             model=t.model, rows=t.rows, bucket=bucket)
        emit("serve:queue", spans.next_span_id(), root_id, 1,
             root_start, queue_s)
        emit("serve:pad", spans.next_span_id(), root_id, 1,
             root_start + queue_s, pad_s)
        emit("serve:compute", spans.next_span_id(), root_id, 1,
             root_start + queue_s + pad_s, compute_s)

    # -- introspection -----------------------------------------------------
    @property
    def latency(self) -> metrics.Histogram:
        """THIS replica's total-latency histogram (the fleet scraper
        exports it as a per-replica labeled series)."""
        return self._latency

    def stats(self) -> Dict[str, float]:
        s = {"admitted": self._admitted.value,
             "shed": self._shed.value,
             "expired": self._expired.value,
             "completed": self._completed.value,
             "failed": self._failed.value,
             "inflight": self.inflight,
             "queue_depth": self._queue.qsize(),
             "pending_rows": self._batcher.pending_rows,
             "p50_ms": round(self._latency.percentile(50), 3),
             "p99_ms": round(self._latency.percentile(99), 3)}
        s.update({f"registry.{k}": v
                  for k, v in self.registry.stats().items()})
        for name, lane in self._lanes.items():
            s.update({f"generate.{name}.{k}": v
                      for k, v in lane.stats().items()})
        return s
