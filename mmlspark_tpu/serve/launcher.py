"""Multi-host fan-out: one supervisor fleet per machine, one control plane.

The layer above :class:`~mmlspark_tpu.serve.supervisor.Supervisor` that
the ROADMAP's "beyond one machine" rung requires, kept deliberately
thin: a :class:`HostLauncher` starts one ``mmlspark-tpu fleet`` process
per host (each of which supervises its own worker processes, writes its
own ``supervisor.*`` event sidecars, and fronts its workers with a local
router), reads each fleet's one-line JSON announce to learn its front
address, and exposes the set as plain
:class:`~mmlspark_tpu.serve.router.HttpReplica` objects — the existing
host-agnostic :class:`~mmlspark_tpu.serve.router.Router` /
:class:`~mmlspark_tpu.observability.aggregate.FleetScraper` stitch them
into one control plane with no new code.

The transport is a seam, not a dependency: :class:`LocalExec` runs the
per-host command on this machine (how tests and single-host smoke runs
exercise the exact production wiring), :class:`SshExec` wraps the same
argv in a non-interactive ``ssh`` invocation. Both reuse
:class:`~mmlspark_tpu.serve.supervisor.ProcessWorker`'s announce
handshake and drain machinery through its ``popen=`` parameter.

Lint Rule 12 extends to this module (a process-management home) and
Rule 15 fences its levers (``launch_host``/``stop_host``) the same way
it fences the supervisor's ``add_slot``/``retire_slot``.
"""
from __future__ import annotations

import os
import shlex
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence

from mmlspark_tpu.observability import events
from mmlspark_tpu.serve.router import HttpReplica
from mmlspark_tpu.serve.supervisor import ProcessWorker
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.launcher")

_LOCAL_HOSTS = ("local", "localhost", "127.0.0.1")


def parse_hosts(spec: str) -> List[str]:
    """``"h1,h2, h3"`` -> ``["h1", "h2", "h3"]`` (order kept, blanks
    dropped, duplicates rejected — two supervisors on one host would
    fight over chips)."""
    hosts = [h.strip() for h in (spec or "").split(",") if h.strip()]
    if len(set(hosts)) != len(hosts):
        raise ValueError(f"duplicate hosts in {spec!r}")
    return hosts


def read_hosts_file(path: str) -> List[str]:
    """One host per line; blank lines and ``#`` comments skipped."""
    hosts: List[str] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if line:
                hosts.append(line)
    if len(set(hosts)) != len(hosts):
        raise ValueError(f"duplicate hosts in {path}")
    return hosts


class LocalExec:
    """Run the per-host command on THIS machine — the transport tests
    (and single-host smoke runs) use to exercise the exact launcher
    wiring without ssh."""

    def __init__(self, host: str = "local"):
        self.host = host

    def wrap(self, argv: Sequence[str]) -> List[str]:
        return list(argv)

    def popen(self, argv: Sequence[str], **kw):
        return subprocess.Popen(self.wrap(argv), **kw)


class SshExec:
    """Run the per-host command over non-interactive ssh. The remote
    command is shell-quoted verbatim; stdout (the fleet's JSON announce
    + logs) rides the ssh channel back, so the same
    :class:`ProcessWorker` handshake works unchanged. The remote
    environment comes from the remote login profile — ``env`` is
    intentionally NOT forwarded (ssh drops it anyway)."""

    def __init__(self, host: str, ssh_args: Sequence[str] = ()):
        self.host = host
        self.ssh_args = list(ssh_args)

    def wrap(self, argv: Sequence[str]) -> List[str]:
        cmd = " ".join(shlex.quote(a) for a in argv)
        return ["ssh", "-o", "BatchMode=yes", *self.ssh_args,
                self.host, "--", cmd]

    def popen(self, argv: Sequence[str], **kw):
        kw["env"] = None  # remote env comes from the remote profile
        return subprocess.Popen(self.wrap(argv), **kw)


def default_exec_factory(host: str):
    """Local names run locally, anything else goes over ssh."""
    if host in _LOCAL_HOSTS:
        return LocalExec(host)
    return SshExec(host)


class HostLauncher:
    """Fan one ``mmlspark-tpu fleet`` supervisor out per host.

    Each host runs its own supervisor (restart-on-crash, chip pinning,
    per-pid event sidecars under ``<events_dir>/host-<host>/``) and
    fronts its workers behind one announced address; the launcher
    collects those addresses as :class:`HttpReplica` objects for the
    caller's router/scraper. ``exec_factory(host)`` is the transport
    seam — tests inject fakes, production uses
    :func:`default_exec_factory`.
    """

    def __init__(self, hosts: Sequence[str], model_flags: Sequence[str], *,
                 replicas_per_host: Optional[int] = None,
                 events_dir: str = "",
                 extra_args: Sequence[str] = (),
                 exec_factory: Optional[Callable] = None,
                 ready_timeout_s: Optional[float] = None):
        hosts = list(hosts)
        if not hosts:
            raise ValueError("launcher needs at least one host")
        if len(set(hosts)) != len(hosts):
            raise ValueError(f"duplicate hosts in {hosts!r}")
        if not model_flags:
            raise ValueError("launcher needs at least one --model flag")
        self.hosts = hosts
        self.model_flags = list(model_flags)
        self.replicas_per_host = int(
            replicas_per_host if replicas_per_host is not None
            else mmlconfig.get("fleet.replicas"))
        self.events_dir = events_dir
        self.extra_args = list(extra_args)
        self.exec_factory = exec_factory if exec_factory is not None \
            else default_exec_factory
        self.ready_timeout_s = float(
            ready_timeout_s if ready_timeout_s is not None
            else mmlconfig.get("fleet.supervisor_ready_timeout_s"))
        self.workers: Dict[str, ProcessWorker] = {}
        self._replicas: Dict[str, HttpReplica] = {}

    # -- per-host command ---------------------------------------------------
    def host_events_dir(self, host: str) -> str:
        return os.path.join(self.events_dir, f"host-{host}") \
            if self.events_dir else ""

    def build_argv(self, host: str) -> List[str]:
        argv = [sys.executable, "-m", "mmlspark_tpu.cli", "fleet",
                "--replicas", str(self.replicas_per_host)]
        for spec in self.model_flags:
            argv += ["--model", spec]
        hdir = self.host_events_dir(host)
        if hdir:
            argv += ["--events-dir", hdir]
        argv += self.extra_args
        return argv

    # -- levers (lint Rule 15) ----------------------------------------------
    def launch_host(self, host: str) -> HttpReplica:
        """Start one host's fleet and wait for its announce; returns the
        host front's :class:`HttpReplica` (name ``host:<host>``)."""
        if host in self.workers:
            raise ValueError(f"host {host!r} already launched")
        ex = self.exec_factory(host)
        hdir = self.host_events_dir(host)
        log_path = None
        if hdir and (host in _LOCAL_HOSTS or isinstance(ex, LocalExec)):
            os.makedirs(hdir, exist_ok=True)
            log_path = os.path.join(hdir, f"fleet-{host}.log")
        w = ProcessWorker(f"host:{host}", self.build_argv(host),
                          env=None, log_path=log_path, popen=ex.popen)
        self.workers[host] = w
        if events.recording_enabled():
            events.emit("launcher", "launch", host=host, pid=w.pid)
        logger.info("launching fleet on %s pid=%s", host, w.pid)
        if not w.await_announce(self.ready_timeout_s):
            raise RuntimeError(
                f"host {host!r} fleet did not announce within "
                f"{self.ready_timeout_s:.0f}s")
        addr = str(w.addr)
        rep = HttpReplica(addr if "://" in addr else "http://" + addr,
                          name=f"host:{host}")
        self._replicas[host] = rep
        return rep

    def stop_host(self, host: str,
                  drain_timeout_s: Optional[float] = None) -> bool:
        """SIGTERM one host's fleet (its supervisor drains its workers),
        SIGKILL past the drain budget. Idempotent on unknown hosts."""
        w = self.workers.pop(host, None)
        self._replicas.pop(host, None)
        if w is None:
            return False
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else mmlconfig.get("serving.drain_timeout_s"))
        if w.poll() is None:
            w.terminate()
            if w.wait(max(timeout, 0.0)) is None:
                logger.warning("host %s fleet did not drain in %.1fs; "
                               "killing", host, timeout)
                w.kill()
                w.wait(5.0)
        w.close()
        if events.recording_enabled():
            events.emit("launcher", "stop", host=host)
        logger.info("stopped fleet on %s", host)
        return True

    # -- aggregates ---------------------------------------------------------
    def launch(self) -> List[HttpReplica]:
        """Launch every host; on any failure, stop what already started
        (no half-launched control plane) and re-raise."""
        try:
            return [self.launch_host(h) for h in self.hosts]
        except Exception:
            self.shutdown()
            raise

    def replicas(self) -> List[HttpReplica]:
        return [self._replicas[h] for h in self.hosts
                if h in self._replicas]

    def shutdown(self, drain_timeout_s: Optional[float] = None) -> None:
        for host in list(self.workers):
            self.stop_host(host, drain_timeout_s=drain_timeout_s)

    def stats(self) -> Dict[str, object]:
        return {
            "hosts": {
                h: {"pid": w.pid,
                    "running": w.poll() is None,
                    "addr": str(w.addr),
                    "announce": dict(w.announce)}
                for h, w in self.workers.items()},
            "desired_hosts": len(self.hosts),
            "live_hosts": sum(1 for w in self.workers.values()
                              if w.poll() is None),
        }

    def __enter__(self) -> "HostLauncher":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
