"""Fleet: N serving replicas behind one router, rolled out without downtime.

The deployment-unit layer above :class:`~mmlspark_tpu.serve.server.Server`
(one process, one executor, one bounded queue) and
:class:`~mmlspark_tpu.serve.router.Router` (spread + failover + fairness):

- :class:`InProcessReplica` — a live :class:`Server` behind the Replica
  protocol, plus ``kill()``: the chaos lever that makes a replica die the
  way a preempted pod does (in-flight work fails retryably, subsequent
  calls are transport-dead), so failover is exercised for real.
- :class:`Fleet` — builds N in-process replicas over the SAME model
  objects (they share one ``_cached_jit`` program cache: N replicas cost
  one compile, the whole point of in-process replication on one host) and
  fronts them with a :class:`Router`.
- :meth:`Fleet.rollout` — the zero-downtime model-version rollout, one
  replica at a time: **deploy** (shift the replica's router weight to 0 —
  no new traffic, in-flight finishes) -> **drain** (wait for in-flight 0)
  -> **swap** (:meth:`ModelRegistry.replace` — atomic cutover, old entry
  evicted/retired) -> **warm** (build the new version's apply and
  AOT-compile its bucket against a sample row BEFORE it takes traffic, so
  the first real request never pays the compile) -> **shift** (restore
  weight). The other replicas keep serving the whole time; the observable
  trail is ``rollout.*`` events plus the report dict returned.

HTTP replicas (separate serving processes) ride the same router via
:class:`~mmlspark_tpu.serve.router.HttpReplica`; this module's Fleet is
the single-host form the CLI (``mmlspark-tpu serve --replicas N``) and
the chaos harness drive.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from mmlspark_tpu.observability import events
from mmlspark_tpu.serve.router import ReplicaUnavailable, Router
from mmlspark_tpu.serve.server import (
    Server, ServerClosed, ServerOverloaded,
)
from mmlspark_tpu.utils import config as mmlconfig
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.fleet")


class RolloutAborted(RuntimeError):
    """A rollout guard stopped the rollout after the canary took traffic
    on the new version and the SLO started burning. The canary KEEPS the
    new version (it is already warmed and back in rotation — yanking it
    mid-burn would double the disruption); every replica after it still
    serves the old one. The operator decides between re-running the
    rollout and rolling the canary back."""


class InProcessReplica:
    """One in-process :class:`Server` behind the Replica protocol.

    ``submit`` blocks on the server's future so the router sees a plain
    call with plain exceptions; a replica that has been :meth:`kill`-ed
    (or whose server closed under the request) surfaces as
    :class:`ReplicaUnavailable` — the transport-dead signal the router's
    failover path keys on, distinct from a shed (the server answering
    "full")."""

    def __init__(self, name: str, server: Server):
        self.name = name
        self.server = server
        self._dead = False

    @property
    def capacity_rows(self) -> int:
        return self.server.capacity_rows

    def submit(self, model: str, x, deadline_ms: Optional[float] = None,
               trace_id: str = "") -> np.ndarray:
        if self._dead:
            raise ReplicaUnavailable(f"replica {self.name} is dead")
        try:
            fut = self.server.submit_async(model, x, deadline_ms,
                                           trace_id=trace_id or None)
            return fut.result()
        except ServerClosed as e:
            raise ReplicaUnavailable(
                f"replica {self.name} closed") from e
        except ServerOverloaded as e:
            if self._dead or not self.server.health()["live"]:
                # the kill landed while this request was in flight: its
                # ticket failed retryably, but for the ROUTER this is a
                # dying replica, not a full one — failover, don't shed
                raise ReplicaUnavailable(
                    f"replica {self.name} died mid-request") from e
            raise

    def submit_generate(self, model: str, prompt,
                        max_new_tokens: Optional[int] = None,
                        **kw) -> Dict:
        """Generative counterpart of :meth:`submit`: blocks on the lane's
        future and maps a dead/closed replica to
        :class:`ReplicaUnavailable`. Generation state (KV blocks, sampled
        tokens) dies with the replica, so the router RESTARTS the sequence
        from its prompt on a survivor — seeded sampling makes the replay
        token-identical."""
        if self._dead:
            raise ReplicaUnavailable(f"replica {self.name} is dead")
        try:
            fut = self.server.submit_generate(
                model, prompt, max_new_tokens, **kw)
            return fut.result()
        except ServerClosed as e:
            raise ReplicaUnavailable(
                f"replica {self.name} closed") from e
        except ServerOverloaded as e:
            if self._dead or not self.server.health()["live"]:
                raise ReplicaUnavailable(
                    f"replica {self.name} died mid-generation") from e
            raise

    def health(self) -> Dict[str, object]:
        if self._dead:
            return {"live": False, "ready": False, "state": "dead"}
        return self.server.health()

    def models(self) -> List[str]:
        return self.server.registry.names()

    def kill(self) -> None:
        """Die like a preempted pod: no drain, in-flight tickets fail
        retryably ("retry elsewhere"), health goes dead. Idempotent by
        contract — the host chaos scenario double-kills under race, so a
        second kill is a silent no-op (no error, no duplicate event)."""
        if self._dead:
            return
        self._dead = True
        logger.warning("replica %s killed", self.name)
        if events.recording_enabled():
            events.emit("fleet", "kill", replica=self.name)
        self.server.close(drain=False, timeout_s=0.5)


class Fleet:
    """N in-process replicas + router + rolling rollout, one object.

    ``models`` maps serving names to fitted models, exactly as
    :class:`Server` takes them; every replica registers the SAME model
    objects, so the jit/program caches are shared and N replicas compile
    once. Server knobs (``queue_depth``, ``max_batch``, ...) pass through
    to every replica; router knobs (``failover_attempts``,
    ``tenant_weights``, ...) to the router. ``clock``/``sleep`` are
    injectable for deterministic tests and reach both layers.
    """

    def __init__(self, models: Dict[str, object], *,
                 replicas: Optional[int] = None,
                 clock: Optional[Callable[[], float]] = None,
                 sleep: Optional[Callable[[float], None]] = None,
                 start: bool = True,
                 server_kwargs: Optional[Dict] = None,
                 **router_kwargs):
        n = int(replicas if replicas is not None
                else mmlconfig.get("fleet.replicas"))
        if n < 1:
            raise ValueError(f"fleet needs >= 1 replica, got {n}")
        self._sleep = sleep if sleep is not None else time.sleep
        skw = dict(server_kwargs or {})
        skw.setdefault("clock", clock)
        # kept so scale_up() builds replicas identical to the founding
        # set (same model OBJECTS -> shared jit caches, no new compiles)
        self._models = models
        self._server_kwargs = skw
        self._start = start
        self._next_idx = n
        # current mesh placement, canonical 'DxT[xP]' text ('' = the
        # models' own meshSpec, untouched). reshard() maintains it; the
        # autopilot's reshard lever reads it to veto "already there".
        self.mesh_shape: str = ""
        self.servers = [Server(models, start=start, **skw)
                        for _ in range(n)]
        self.replicas = [InProcessReplica(f"r{i}", srv)
                         for i, srv in enumerate(self.servers)]
        self.router = Router(self.replicas, clock=clock, sleep=sleep,
                             **router_kwargs)
        self._closed = False

    # -- serving surface (delegates; the HTTP front-end binds the router) --
    def submit(self, model: str, x, deadline_ms: Optional[float] = None,
               **kw) -> np.ndarray:
        return self.router.submit(model, x, deadline_ms, **kw)

    def submit_generate(self, model: str, prompt,
                        max_new_tokens: Optional[int] = None,
                        **kw) -> Dict:
        return self.router.submit_generate(model, prompt,
                                           max_new_tokens, **kw)

    def health(self) -> Dict[str, object]:
        return self.router.health()

    def stats(self) -> Dict[str, object]:
        s = self.router.stats()
        s["servers"] = {r.name: r.server.stats() for r in self.replicas}
        return s

    def kill(self, index: int) -> None:
        """Chaos lever: kill replica ``index`` without telling the router
        — failover and health probing must DISCOVER the death. Idempotent
        like the replica-level kill: double-killing the same index under
        a chaos race is a no-op, not an error."""
        self.replicas[index].kill()

    # -- scale actuators (lint Rule 15; the autopilot's lever) --------------
    def scale_up(self) -> str:
        """Add one replica over the SAME model objects as the founding
        set — shared jit/program caches mean the new replica costs zero
        new compiles (the ``steady_compiles == 0`` invariant holds
        through scale events). It enters the router ready at weight 1.0;
        returns the new replica's name."""
        if self._closed:
            raise RuntimeError("fleet is closed")
        srv = Server(self._models, start=self._start,
                     **self._server_kwargs)
        name = f"r{self._next_idx}"
        self._next_idx += 1
        rep = InProcessReplica(name, srv)
        self.servers.append(srv)
        self.replicas.append(rep)
        self.router.add_replica(rep)
        if events.recording_enabled():
            events.emit("fleet", "scale_up", replica=name,
                        replicas=len(self.replicas))
        return name

    def scale_down(self, name: str,
                   drain_timeout_s: Optional[float] = None) -> None:
        """Retire one replica gracefully: out of the router rotation
        first (no new traffic), then drain in-flight work, then close its
        server. The inverse of :meth:`scale_up`; killing is what
        :meth:`kill` is for. Idempotent on an unknown or already-retired
        name — the autopilot racing a crash can double-retire, and that
        must surface as an event + no-op, not a KeyError inside the
        control loop."""
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else mmlconfig.get("serving.drain_timeout_s"))
        rep = next((r for r in self.replicas if r.name == name), None)
        if rep is None:
            logger.info("scale_down(%r): no such replica (already "
                        "retired?) — no-op", name)
            if events.recording_enabled():
                events.emit("fleet", "scale_down_noop", replica=name,
                            replicas=len(self.replicas))
            return
        self.router.remove_replica(name)
        if not rep._dead:
            try:
                self._wait_idle(rep.server, timeout)
            finally:
                rep.server.close(drain=True)
        self.replicas.remove(rep)
        if rep.server in self.servers:
            self.servers.remove(rep.server)
        if events.recording_enabled():
            events.emit("fleet", "scale_down", replica=name,
                        replicas=len(self.replicas))

    # -- rolling rollout ----------------------------------------------------
    def rollout(self, name: str, model, version: str,
                warm_x=None,
                drain_timeout_s: Optional[float] = None,
                guard: Optional[Callable[[str], Optional[str]]] = None,
                ) -> Dict:
        """Roll ``name`` to ``model``@``version`` across the fleet with
        zero downtime: one replica at a time leaves rotation, drains,
        swaps, warms, and returns — the rest keep serving throughout.

        ``warm_x`` (a sample row or batch) makes the warm step score once
        through the replica BEFORE it takes traffic, building the apply
        AND AOT-compiling its bucket; without it the warm step only
        builds the apply (the first request pays bucket compilation).
        The first replica is the canary: its warm failure aborts the
        rollout with every other replica still on the old version.

        ``guard`` is the autopilot's rollout-abort hook: called with the
        replica name AFTER each replica is back in rotation on the new
        version; a non-empty return value (the reason, e.g. "canary SLO
        burning") raises :class:`RolloutAborted` before the next replica
        is touched. See :meth:`~mmlspark_tpu.control.autopilot.Autopilot.
        rollout_guard`."""
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else mmlconfig.get("serving.drain_timeout_s"))
        report: Dict = {"model": name, "version": version, "replicas": []}
        if events.recording_enabled():
            events.emit("rollout", "deploy", model=name, version=version,
                        replicas=len(self.replicas))
        for rep in list(self.replicas):  # scale events must not shift it
            if rep._dead:
                report["replicas"].append(
                    {"replica": rep.name, "status": "skipped_dead"})
                continue
            step = {"replica": rep.name, "status": "updated"}
            weight = self.router._handles[rep.name].weight
            # deploy: out of rotation — no NEW traffic; in-flight finishes
            self.router.set_weight(rep.name, 0.0)
            try:
                self._wait_idle(rep.server, timeout)
                entry = rep.server.registry.replace(name, model, version)
                self._warm(rep, entry, name, warm_x)
            except Exception:
                # canary semantics: put the replica back in rotation on
                # whatever version its registry now holds, then abort —
                # replicas not yet touched still serve the old version
                self.router.set_weight(rep.name, weight)
                if events.recording_enabled():
                    events.emit("rollout", "abort", model=name,
                                version=version, replica=rep.name)
                raise
            # shift: warmed replica takes traffic again
            self.router.set_weight(rep.name, weight)
            if events.recording_enabled():
                events.emit("rollout", "shift", model=name,
                            version=version, replica=rep.name,
                            weight=weight)
            report["replicas"].append(step)
            if guard is not None:
                reason = guard(rep.name)
                if reason:
                    step["status"] = "aborted_after"
                    if events.recording_enabled():
                        events.emit("rollout", "abort", model=name,
                                    version=version, replica=rep.name,
                                    reason=str(reason))
                    raise RolloutAborted(
                        f"rollout of {name}@{version} aborted at "
                        f"{rep.name}: {reason}")
        if events.recording_enabled():
            events.emit("rollout", "done", model=name, version=version,
                        updated=sum(1 for r in report["replicas"]
                                    if r["status"] == "updated"))
        report["versions"] = {r.name: r.server.registry.versions()
                              for r in self.replicas if not r._dead}
        return report

    # -- elastic mesh (lint Rule 15; the autopilot's fifth lever) -----------
    def reshard(self, mesh_shape, *, models: Optional[Sequence[str]] = None,
                warm_x=None,
                drain_timeout_s: Optional[float] = None) -> Dict:
        """Change the mesh placement of the SERVING fleet with zero
        downtime: every served model's SAME checkpoint is loaded into a
        NEW mesh placement, one replica at a time, through the exact
        drain -> swap -> warm -> shift sequence :meth:`rollout` uses.

        ``mesh_shape`` is the ``parallel.mesh_shape`` shorthand
        (``'4x2'``, ``'2x2x2'`` for a 3-D ``(data, tensor, pipe)``
        topology), a :class:`~mmlspark_tpu.parallel.mesh.MeshSpec`, or
        ``None`` to return to the single-device fast path. One resharded
        copy per model is shared by EVERY replica — the fleet pays one
        compile per program, and with ``runtime.compile_cache_dir`` set a
        pre-warmed target placement loads serialized executables instead
        (``steady_compiles == 0`` through the whole reshard). Scores are
        bit-identical throughout: same checkpoint, same numerics path,
        only the placement moves.

        Generate lanes re-shard with their model: the old lane drains
        (in-flight sequences complete on the OLD placement) then closes —
        anything still unfinished fails retryably and the router
        failover-restarts it token-identically — and a fresh lane with a
        KV arena on the NEW placement is built before the replica takes
        traffic again.

        A placement that cannot fit the registry budget raises
        :class:`~mmlspark_tpu.serve.registry.PlacementOverBudget` from
        the FIRST replica's swap, before any entry is dropped — the
        whole reshard degrades to a no-op with every replica still
        serving. A replica killed mid-reshard is recorded
        (``status="died"``) and skipped; the survivors complete.

        ``warm_x`` is a sample row/batch (single served model) or a
        ``{name: sample}`` dict; as in :meth:`rollout` it AOT-compiles
        each bucket before the replica re-enters rotation."""
        from mmlspark_tpu.parallel.mesh import MeshSpec, parse_mesh_shape
        if isinstance(mesh_shape, str) and mesh_shape:
            spec = parse_mesh_shape(mesh_shape)
        elif isinstance(mesh_shape, MeshSpec) or mesh_shape is None:
            spec = mesh_shape
        else:
            raise TypeError(
                f"mesh_shape must be a 'DxT[xP]' string, MeshSpec, or "
                f"None; got {type(mesh_shape).__name__}")
        shape_text = self._shape_text(spec)
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else mmlconfig.get("serving.drain_timeout_s"))
        names = list(models) if models is not None else \
            sorted(self._models)
        for n in names:
            if n not in self._models:
                raise KeyError(f"unknown model {n!r}; fleet serves "
                               f"{sorted(self._models)}")
        # one resharded copy per model, shared fleet-wide: same
        # checkpoint (deep-copied state), new placement via meshSpec —
        # the _cached_jit key includes repr(meshSpec), so old and new
        # placements never collide in the program caches
        copies = {}
        for n in names:
            m = self._models[n].copy()
            setter = getattr(m, "set_params", None)
            if setter is None:
                raise TypeError(
                    f"model {n!r} ({type(m).__name__}) does not carry a "
                    "meshSpec param; reshard needs JaxModel-style models")
            setter(meshSpec=spec)
            copies[n] = m
        warm = dict(warm_x) if isinstance(warm_x, dict) else \
            {n: warm_x for n in names}
        report: Dict = {"mesh_shape": shape_text, "models": names,
                        "replicas": []}
        if events.recording_enabled():
            events.emit("reshard", "start", mesh_shape=shape_text,
                        models=names, replicas=len(self.replicas))
        for rep in list(self.replicas):  # scale events must not shift it
            if rep._dead:
                report["replicas"].append(
                    {"replica": rep.name, "status": "skipped_dead"})
                continue
            step = {"replica": rep.name, "status": "resharded"}
            weight = self.router._handles[rep.name].weight
            self.router.set_weight(rep.name, 0.0)
            try:
                self._wait_idle(rep.server, timeout)
                for n in names:
                    # drain + retire the OLD placement's lane first: its
                    # arena and programs are bound to the entry we are
                    # about to replace
                    had_lane = n in rep.server._lanes
                    if had_lane:
                        self._wait_lane_idle(rep.server._lanes[n],
                                             timeout)
                        rep.server.reset_lane(n, timeout_s=timeout)
                    version = rep.server.registry.versions().get(n, "v1")
                    entry = rep.server.registry.replace(
                        n, copies[n], version)
                    self._warm(rep, entry, n, warm.get(n))
                    if had_lane:
                        # fresh lane against the NEW entry: KV arena
                        # re-sharded onto the target placement before
                        # the replica takes traffic
                        rep.server.enable_generate(n)
                    step[f"compiles:{n}"] = entry.compile_count
                    step[f"cache_hits:{n}"] = entry.cache_hits
            except Exception as e:
                if rep._dead or not rep.health()["live"]:
                    # a kill landed mid-reshard: record and move on —
                    # the dead replica is the router's problem
                    # (failover), not the reshard's
                    step["status"] = "died"
                    step["error"] = f"{type(e).__name__}: {e}"
                    report["replicas"].append(step)
                    if events.recording_enabled():
                        events.emit("reshard", "replica_died",
                                    replica=rep.name,
                                    mesh_shape=shape_text)
                    continue
                # no-op semantics: this replica back in rotation on its
                # CURRENT placement, then surface the failure
                self.router.set_weight(rep.name, weight)
                if events.recording_enabled():
                    events.emit("reshard", "abort", replica=rep.name,
                                mesh_shape=shape_text,
                                reason=f"{type(e).__name__}: {e}")
                raise
            self.router.set_weight(rep.name, weight)
            report["replicas"].append(step)
            if events.recording_enabled():
                events.emit("reshard", "shift", replica=rep.name,
                            mesh_shape=shape_text, weight=weight)
        # scale_up() must build replicas on the NEW placement, and a
        # repeat reshard must copy from the resharded models
        self._models = dict(self._models)
        self._models.update(copies)
        self.mesh_shape = shape_text
        report["resharded"] = sum(1 for r in report["replicas"]
                                  if r["status"] == "resharded")
        if events.recording_enabled():
            events.emit("reshard", "done", mesh_shape=shape_text,
                        resharded=report["resharded"],
                        replicas=len(self.replicas))
        return report

    @staticmethod
    def _shape_text(spec) -> str:
        """Canonical 'DxT[xP]' text for a MeshSpec ('' for None) — the
        comparison key the autopilot's reshard lever uses."""
        if spec is None:
            return ""
        parts = [spec.data, spec.tensor]
        if spec.pipe != 1:
            parts.append(spec.pipe)
        return "x".join(str(p) for p in parts)

    def _wait_lane_idle(self, lane, timeout_s: float) -> None:
        """Bounded wait for a generate lane's in-flight sequences to
        finish on the OLD placement. Best-effort: on timeout the lane's
        close fails the stragglers retryably and the router restarts
        them token-identically elsewhere — either way no tokens are
        lost."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while time.monotonic() < deadline:
            s = lane.stats()
            if s.get("waiting", 0) + s.get("active", 0) \
                    + s.get("prefilling", 0) <= 0:
                return
            self._sleep(0.005)

    def _wait_idle(self, server: Server, timeout_s: float) -> None:
        """Drain: wait for the replica's in-flight count to hit zero
        (admission continues — only the ROUTER stopped sending; a direct
        client could still reach it, which is fine: rollout waits)."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while server.inflight > 0:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replica did not drain within {timeout_s:.1f}s "
                    f"({server.inflight} in flight)")
            self._sleep(0.005)

    def _warm(self, rep: InProcessReplica, entry, name: str,
              warm_x) -> None:
        """Warm the swapped entry before it takes traffic: build the
        apply (device-resident params), and when a sample is given score
        it end to end so the bucket's program is AOT-compiled. The bucket
        program funnels through :mod:`mmlspark_tpu.compile_cache`
        (``ModelEntry._compile``), so with ``runtime.compile_cache_dir``
        set each replica's warm LOADS the serialized executable instead of
        recompiling — the per-replica rollout recompile tax this cache
        exists to kill. The warm event carries the entry's hit/compile
        counts so a rollout that silently recompiled is visible."""
        entry.ensure_apply()
        if warm_x is not None:
            rep.submit(name, warm_x)  # lint: allow-direct-replica
        self._prewarm_prefixes(rep, name)
        if events.recording_enabled():
            events.emit("rollout", "warm", model=name,
                        version=entry.version, replica=rep.name,
                        warmed=warm_x is not None,
                        compile_cache_hits=entry.cache_hits,
                        compiles=entry.compile_count)

    def _prewarm_prefixes(self, rep: InProcessReplica, name: str) -> None:
        """Affinity pre-warm: before a swapped replica takes weight,
        replay the fleet's hottest advertised prefix chains through its
        prefill so the canary re-enters rotation already holding the KV
        blocks the router will score it on — without this, every rollout
        resets the replica to zero prefix-hit depth and the affinity
        scorer correctly steers sessions away from the freshest code.
        Best-effort on every axis: no affinity state, no hot prompts, or
        a model without a generate lane all mean "skip", never "abort the
        rollout"."""
        aff = getattr(self.router, "affinity", None)
        if aff is None:
            return
        limit = int(mmlconfig.get("fleet.affinity_prewarm"))
        prompts = aff.hot_prompts(name, limit) if limit > 0 else []
        if not prompts:
            return
        warmed = 0
        for prompt in prompts:
            try:
                rep.server.submit_generate(
                    name, prompt, max_new_tokens=1).result()
                warmed += 1
            except Exception:
                continue    # one cold prompt is not a rollout failure
        if events.recording_enabled():
            events.emit("rollout", "prewarm", model=name,
                        replica=rep.name, prompts=len(prompts),
                        warmed=warmed)

    # -- lifecycle ----------------------------------------------------------
    def drain(self, reason: str = "drain") -> None:
        """Fleet-wide graceful drain (preemption): every live replica
        stops admission, finishes in-flight work, and closes."""
        for rep in self.replicas:
            if not rep._dead:
                rep.server.drain(reason=reason)

    def close(self, drain: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        self.router.close()
        for rep in self.replicas:
            if not rep._dead:
                rep.server.close(drain=drain)

    def __enter__(self) -> "Fleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class ProcessFleet:
    """A supervised process-backed fleet behind the Fleet scale surface.

    The adapter that gives the autopilot real hands: same
    ``scale_up()/scale_down(name)`` actuator signature as :class:`Fleet`,
    but routed through :meth:`~mmlspark_tpu.serve.supervisor.Supervisor.
    add_slot` / :meth:`~mmlspark_tpu.serve.supervisor.Supervisor.
    retire_slot` — each replica is a real OS worker process, spawned warm
    through the shared compile cache and drained through SIGTERM.
    Serving calls delegate to the router (the same
    :class:`~mmlspark_tpu.serve.router.HttpReplica` objects the
    supervisor re-registers across restarts), so
    :class:`~mmlspark_tpu.observability.aggregate.FleetScraper` and
    :class:`~mmlspark_tpu.control.autopilot.Autopilot` accept either
    fleet flavor unchanged. Selected by ``autopilot.scale_backend``.
    """

    def __init__(self, supervisor, router: Router):
        self.supervisor = supervisor
        self.router = router
        if getattr(supervisor, "router", None) is None:
            supervisor.attach_router(router)

    @property
    def replicas(self):
        return self.supervisor.replicas

    # -- serving surface ----------------------------------------------------
    def submit(self, model: str, x, deadline_ms: Optional[float] = None,
               **kw) -> np.ndarray:
        return self.router.submit(model, x, deadline_ms, **kw)

    def submit_generate(self, model: str, prompt,
                        max_new_tokens: Optional[int] = None,
                        **kw) -> Dict:
        return self.router.submit_generate(model, prompt,
                                           max_new_tokens, **kw)

    def health(self) -> Dict[str, object]:
        return self.router.health()

    def stats(self) -> Dict[str, object]:
        s = self.router.stats()
        s["supervisor"] = self.supervisor.stats()
        return s

    # -- scale actuators (lint Rule 15; the autopilot's lever) --------------
    def scale_up(self) -> str:
        """One new supervised worker process: announce handshake,
        ``/readyz``, router registration at full weight — warm through
        the shared compile cache, pinned to its own chip slot. Returns
        the new slot's name."""
        return self.supervisor.add_slot()

    def scale_down(self, name: str,
                   drain_timeout_s: Optional[float] = None) -> None:
        """Gracefully retire one supervised worker (weight→0, SIGTERM
        drain, SIGKILL stragglers). Idempotent on unknown names, like
        :meth:`Fleet.scale_down`."""
        self.supervisor.retire_slot(name, drain_timeout_s=drain_timeout_s)

    def reshard(self, mesh_shape, **kw):
        """Not yet supported for process-backed fleets: each worker
        process owns its model placement, so an elastic reshard means a
        rolling worker restart under a new ``parallel.mesh_shape`` —
        future work. Raising (instead of silently no-oping) keeps the
        autopilot honest: its actuation is recorded as failed and the
        lever cools down."""
        raise NotImplementedError(
            "ProcessFleet.reshard: restart workers with a new "
            "parallel.mesh_shape instead (rolling, via scale_up/"
            "scale_down); in-process Fleet supports live reshard")

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        self.supervisor.shutdown(reason="fleet_close")

    def __enter__(self) -> "ProcessFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
