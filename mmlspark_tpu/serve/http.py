"""Stdlib HTTP front-end for :class:`~mmlspark_tpu.serve.server.Server`.

JSON in, JSON out, zero new dependencies — the transport half of
``mmlspark-tpu serve``. Endpoints:

- ``POST /score`` — body ``{"model": "name", "x": [[...], ...],
  "deadline_ms": 50}`` (``x`` one row or a list of rows; ``deadline_ms``
  optional). 200 -> ``{"y": [[...], ...]}`` (plus the request's
  ``trace_id`` for single-row bodies — grep it in the event log /
  exported trace). Error mapping keeps the
  server's admission semantics visible to HTTP clients:
  ``ServerOverloaded`` -> **503** (with ``Retry-After: 0``, the
  HTTP-native "retryable" signal — ``default_retryable`` already treats
  5xx as retryable on the client side), ``RequestExpired`` -> **504**,
  unknown model / malformed body -> **400**.
- ``POST /generate`` — the generative lane: body ``{"model", "prompt":
  [token ids], "max_new_tokens", "temperature", "top_k", "seed",
  "deadline_ms", "trace_id"}`` -> ``{"tokens", "finish_reason",
  "ttft_ms", "itl_mean_ms", "trace_id"}``. Same error mapping as
  ``/score``; a KV-arena-full shed is a 503 with ``Retry-After``, and a
  deadline lapsing MID-decode is a 200 with the partial stream
  (``finish_reason: "deadline"``) — deadline accounting is per token.
- ``GET /healthz`` — liveness AND readiness in one body
  (``{"status", "live", "ready", "state", "stats"}``): a draining server
  is still ``live`` (in-flight work finishes) but not ``ready`` (stop
  sending traffic) — the split the fleet router routes on.
- ``GET /livez`` / ``GET /readyz`` — the k8s-style probe pair: ``/livez``
  is 200 while the process serves its in-flight work (even draining);
  ``/readyz`` turns 503 the moment admission stops, so a load balancer
  rotates the replica out BEFORE it dies.
- ``GET /models`` — registered model names (+ served versions).
- ``GET /metrics`` — Prometheus text exposition of the process registry.
- ``GET /affinity`` — prefix-digest advertisement: per generative model
  the top-K resident KV prefix chains plus the hash parameters they were
  keyed with, so a fleet scraper can score this replica by expected
  prefix-hit depth without moving any KV bytes.

``ThreadingHTTPServer`` gives one thread per connection; they all funnel
into the server's bounded queue, so concurrency is capped by admission
control, not by transport threads. Request logging routes through the
framework logger (debug level), not BaseHTTPRequestHandler's stderr
``log_message``.
"""
from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from mmlspark_tpu.observability import metrics
from mmlspark_tpu.serve.server import (
    RequestExpired, ServeError, Server, ServerClosed, ServerOverloaded,
)
from mmlspark_tpu.utils.logging import get_logger

logger = get_logger("serve.http")

MAX_BODY_BYTES = 64 * 1024 * 1024   # one request never buffers more


def _fmt_after(seconds: float) -> str:
    """Retry-After header value: integral seconds render as delta-seconds
    per RFC 7231 ("0", "1"); sub-second asks keep the decimal — our own
    clients (HttpReplica, the retry layer) parse floats."""
    s = float(seconds)
    return str(int(s)) if s.is_integer() else str(s)


def make_handler(server: Server):
    """Handler class bound to one :class:`Server` (stdlib handlers are
    instantiated per request; the closure carries the server)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # route, don't print
            logger.debug("http %s", fmt % args)

        def _reply(self, code: int, payload: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/healthz":
                # liveness and readiness, split: a draining server is
                # still LIVE (in-flight work finishes, /healthz answers)
                # but no longer READY for new traffic — routers read
                # "draining" and rotate it out before it stops being alive
                h = server.health()
                status = "ok" if h["ready"] else h["state"]
                self._reply(200, {"status": status, **h,
                                  "stats": server.stats()})
            elif self.path == "/livez":
                h = server.health()
                self._reply(200 if h["live"] else 503, h)
            elif self.path == "/readyz":
                h = server.health()
                self._reply(200 if h["ready"] else 503, h)
            elif self.path == "/models":
                reg = server.registry
                payload = {"models": reg.names()}
                if hasattr(reg, "versions"):
                    payload["versions"] = reg.versions()
                self._reply(200, payload)
            elif self.path == "/affinity":
                # prefix-digest advertisement: the metrics-adjacent JSON
                # the fleet scraper pulls to score replicas by expected
                # prefix-hit depth. Per generative model: the top-K
                # resident chains plus the hashing parameters (kv dtype,
                # block size) the chain keys were seeded with — scorers
                # must hash prompts with the ADVERTISED params, never
                # guess them.
                tail = ".kv.resident_chains"
                digests = {}
                stats = server.stats()
                for k, v in stats.items():
                    if not (k.startswith("generate.") and k.endswith(tail)
                            and isinstance(v, list)):
                        continue
                    model = k[len("generate."):-len(tail)]
                    digests[model] = {
                        "chains": v,
                        "kv_dtype": str(stats.get(
                            f"generate.{model}.kv.kv_dtype") or ""),
                        "block_tokens": stats.get(
                            f"generate.{model}.kv.block_tokens"),
                    }
                self._reply(200, {"digests": digests})
            elif self.path == "/metrics":
                text = metrics.get_registry().prometheus_text()
                body = text.encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/generate":
                self._post_generate()
                return
            if self.path != "/score":
                self._reply(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                if n > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"})
                    return
                req = json.loads(self.rfile.read(n))
                model = req["model"]
                x = np.asarray(req["x"])
                deadline_ms = req.get("deadline_ms")
                # a fleet router threads its trace_id through so one id
                # correlates the whole failover chain across replicas
                rid = str(req.get("trace_id") or "") or None
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            trace_id = ""
            try:
                if x.ndim <= 1:
                    fut = server.submit_async(model, x, deadline_ms,
                                              trace_id=rid)
                    trace_id = getattr(fut, "trace_id", "")
                    y = fut.result()
                else:
                    # multi-row bodies fan out into several tickets; no
                    # single id to return
                    y = server.submit_many(model, x, deadline_ms)
            except ServerOverloaded as e:
                # Retry-After carries the server's own ask (a draining
                # replica says 1s — come back to the pool, not instantly
                # to us; a full queue says serving.retry_after_s)
                after = getattr(e, "retry_after", None)
                if after is None:
                    after = 1.0 if server.draining else 0.0
                self._reply(503, {"error": str(e), "retryable": True,
                                  "retry_after": after},
                            headers={"Retry-After": _fmt_after(after)})
            except ServerClosed as e:
                self._reply(503, {"error": str(e), "retryable": True},
                            headers={"Retry-After": "1"})
            except RequestExpired as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError) as e:
                self._reply(400, {"error": str(e)})
            except ServeError as e:
                self._reply(500, {"error": str(e)})
            else:
                payload = {"y": np.asarray(y).tolist()}
                if trace_id:
                    payload["trace_id"] = trace_id
                self._reply(200, payload)

        def _post_generate(self):
            """``POST /generate`` — body ``{"model", "prompt": [ids...],
            "max_new_tokens", "temperature", "top_k", "seed", "eos_id",
            "deadline_ms", "trace_id"}``. Same error mapping as
            ``/score`` (shed -> 503 + Retry-After, pre-prefill expiry ->
            504, bad body -> 400). Deadline accounting is PER TOKEN: a
            deadline that lapses mid-decode returns 200 with the partial
            token stream and ``finish_reason: "deadline"`` — the tokens
            already sampled are not worthless."""
            try:
                n = int(self.headers.get("Content-Length", 0))
                if n > MAX_BODY_BYTES:
                    self._reply(413, {"error": "body too large"})
                    return
                req = json.loads(self.rfile.read(n))
                model = req["model"]
                prompt = [int(t) for t in req["prompt"]]
                kw = dict(
                    max_new_tokens=req.get("max_new_tokens"),
                    temperature=float(req.get("temperature", 0.0)),
                    top_k=int(req.get("top_k", 0)),
                    seed=int(req.get("seed", 0)),
                    eos_id=req.get("eos_id"),
                    deadline_ms=req.get("deadline_ms"),
                    trace_id=str(req.get("trace_id") or "") or None)
            except (KeyError, ValueError, TypeError) as e:
                self._reply(400, {"error": f"bad request: {e}"})
                return
            try:
                fut = server.submit_generate(model, prompt, **kw)
                out = fut.result()
            except ServerOverloaded as e:
                after = getattr(e, "retry_after", None)
                if after is None:
                    after = 1.0 if server.draining else 0.0
                self._reply(503, {"error": str(e), "retryable": True,
                                  "retry_after": after},
                            headers={"Retry-After": _fmt_after(after)})
            except ServerClosed as e:
                self._reply(503, {"error": str(e), "retryable": True},
                            headers={"Retry-After": "1"})
            except RequestExpired as e:
                self._reply(504, {"error": str(e)})
            except (KeyError, ValueError) as e:
                self._reply(400, {"error": str(e)})
            except ServeError as e:
                self._reply(500, {"error": str(e)})
            else:
                self._reply(200, out)

    return Handler


def serve_http(server: Server, host: str = "127.0.0.1", port: int = 8080,
               poll_s: float = 0.5) -> Tuple[ThreadingHTTPServer, str]:
    """Bind and return ``(httpd, "host:port")`` without blocking; callers
    run ``httpd.serve_forever()`` (the CLI does) or drive
    ``handle_request`` themselves (tests)."""
    httpd = ThreadingHTTPServer((host, port), make_handler(server))
    httpd.timeout = poll_s
    addr = f"{httpd.server_address[0]}:{httpd.server_address[1]}"
    logger.info("serving on http://%s (models: %s)",
                addr, ", ".join(server.registry.names()) or "none")
    return httpd, addr
