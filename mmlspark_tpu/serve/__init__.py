"""Online serving: dynamic micro-batching inference under SLO telemetry.

The request-level counterpart to ``JaxModel.transform``'s whole-frame
scoring — see ``docs/SERVING.md`` for architecture, the ``serving.*``
config namespace, and overload/retry semantics.
"""
from mmlspark_tpu.serve.batcher import (      # noqa: F401
    MicroBatcher, Ticket, bucket_for, default_buckets, parse_buckets,
)
from mmlspark_tpu.serve.registry import ModelEntry, ModelRegistry  # noqa: F401
from mmlspark_tpu.serve.server import (        # noqa: F401
    RequestExpired, ServeError, Server, ServerClosed, ServerOverloaded,
)

__all__ = [
    "MicroBatcher", "Ticket", "bucket_for", "default_buckets",
    "parse_buckets", "ModelEntry", "ModelRegistry", "Server",
    "ServeError", "ServerOverloaded", "RequestExpired", "ServerClosed",
]
