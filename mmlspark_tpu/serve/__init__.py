"""Online serving: dynamic micro-batching inference under SLO telemetry.

The request-level counterpart to ``JaxModel.transform``'s whole-frame
scoring — see ``docs/SERVING.md`` for architecture, the ``serving.*`` /
``fleet.*`` config namespaces, and overload/retry/failover semantics.
One :class:`Server` is a replica; a :class:`Fleet` is N of them behind a
health-checked :class:`Router` with failover, per-tenant fairness, and
zero-downtime rolling rollout. The generative lane
(:class:`GenerateLane` + :class:`KVCacheManager`) adds continuous-batched
token decoding over a paged KV arena beside the scoring path.
"""
from mmlspark_tpu.serve.batcher import (      # noqa: F401
    MicroBatcher, Ticket, bucket_for, default_buckets, parse_buckets,
)
from mmlspark_tpu.serve.fleet import Fleet, InProcessReplica  # noqa: F401
from mmlspark_tpu.serve.generate import (      # noqa: F401
    ContinuousBatcher, GenerateLane, GenerateRequest, GenerativeEntry,
)
from mmlspark_tpu.serve.kvcache import (       # noqa: F401
    KVCacheManager, blocks_needed,
)
from mmlspark_tpu.serve.registry import ModelEntry, ModelRegistry  # noqa: F401
from mmlspark_tpu.serve.router import (        # noqa: F401
    HttpReplica, ReplicaUnavailable, Router, TenantThrottled,
    WeightedFairAdmission,
)
from mmlspark_tpu.serve.server import (        # noqa: F401
    RequestExpired, ServeError, Server, ServerClosed, ServerOverloaded,
)
from mmlspark_tpu.serve.supervisor import (    # noqa: F401
    ProcessSpawner, Supervisor,
)

__all__ = [
    "MicroBatcher", "Ticket", "bucket_for", "default_buckets",
    "parse_buckets", "ModelEntry", "ModelRegistry", "Server",
    "ServeError", "ServerOverloaded", "RequestExpired", "ServerClosed",
    "Fleet", "InProcessReplica", "HttpReplica", "Router",
    "ReplicaUnavailable", "TenantThrottled", "WeightedFairAdmission",
    "ContinuousBatcher", "GenerateLane", "GenerateRequest",
    "GenerativeEntry", "KVCacheManager", "blocks_needed",
    "Supervisor", "ProcessSpawner",
]
