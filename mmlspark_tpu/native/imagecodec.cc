// Native image codec + prefetching decode pool.
//
// The TPU-native counterpart of the reference's prebuilt OpenCV JNI layer
// (org.opencv % opencv_jni, loaded per-partition via NativeLoader —
// core/env/src/main/scala/NativeLoader.java). Exposed through ctypes
// (mmlspark_tpu/utils/native_loader.py) instead of JNI.
//
// Output convention: row-major uint8 BGR, matching the reference ImageSchema
// (core/schema/src/main/scala/ImageSchema.scala:18-23).
//
// Build: g++ -O2 -fPIC -shared imagecodec.cc -o libmmlimage.so -ljpeg -lpng -lpthread

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <csetjmp>
#include <thread>
#include <vector>
#include <queue>
#include <mutex>
#include <condition_variable>

#include <jpeglib.h>
#include <png.h>

extern "C" {

// ---------------------------------------------------------------- JPEG
struct mml_jpeg_err {
  struct jpeg_error_mgr pub;
  jmp_buf jb;
};

static void mml_jpeg_error_exit(j_common_ptr cinfo) {
  mml_jpeg_err* err = reinterpret_cast<mml_jpeg_err*>(cinfo->err);
  longjmp(err->jb, 1);
}

// Decode JPEG bytes to malloc'd BGR buffer. Returns 0 on success.
int mml_decode_jpeg(const unsigned char* data, long size,
                    unsigned char** out, int* width, int* height) {
  struct jpeg_decompress_struct cinfo;
  struct mml_jpeg_err jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = mml_jpeg_error_exit;
  // volatile: assigned between setjmp and a potential longjmp
  unsigned char* volatile buf = nullptr;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    free(buf);
    return 1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return 1;
  }
  cinfo.out_color_space = JCS_EXT_BGR;  // decode straight to BGR
  jpeg_start_decompress(&cinfo);
  const int w = cinfo.output_width, h = cinfo.output_height;
  const int stride = w * 3;
  buf = static_cast<unsigned char*>(malloc(static_cast<size_t>(stride) * h));
  if (!buf) { jpeg_destroy_decompress(&cinfo); return 1; }
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = buf + static_cast<size_t>(cinfo.output_scanline) * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out = buf;
  *width = w;
  *height = h;
  return 0;
}

// Encode BGR buffer to JPEG (quality q). Returns 0 on success.
int mml_encode_jpeg(const unsigned char* bgr, int width, int height, int q,
                    unsigned char** out, unsigned long* out_size) {
  struct jpeg_compress_struct cinfo;
  struct mml_jpeg_err jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = mml_jpeg_error_exit;
  unsigned char* volatile mem = nullptr;
  unsigned long mem_size = 0;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_compress(&cinfo);
    free(mem);
    return 1;
  }
  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, const_cast<unsigned char**>(&mem), &mem_size);
  cinfo.image_width = width;
  cinfo.image_height = height;
  cinfo.input_components = 3;
  cinfo.in_color_space = JCS_EXT_BGR;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, q, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  const int stride = width * 3;
  while (cinfo.next_scanline < cinfo.image_height) {
    const unsigned char* row = bgr + static_cast<size_t>(cinfo.next_scanline) * stride;
    jpeg_write_scanlines(&cinfo, const_cast<unsigned char**>(&row), 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  *out = mem;
  *out_size = mem_size;
  return 0;
}

// ---------------------------------------------------------------- PNG
struct mml_png_reader {
  const unsigned char* data;
  size_t size;
  size_t pos;
};

static void mml_png_read(png_structp png, png_bytep out, png_size_t n) {
  mml_png_reader* r = static_cast<mml_png_reader*>(png_get_io_ptr(png));
  if (r->pos + n > r->size) { png_error(png, "eof"); }
  memcpy(out, r->data + r->pos, n);
  r->pos += n;
}

int mml_decode_png(const unsigned char* data, long size,
                   unsigned char** out, int* width, int* height) {
  if (png_sig_cmp(data, 0, 8)) return 1;
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING,
                                           nullptr, nullptr, nullptr);
  if (!png) return 1;
  png_infop info = png_create_info_struct(png);
  // volatile: assigned between setjmp and a possible longjmp
  unsigned char* volatile buf = nullptr;
  if (!info || setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    free(buf);
    return 1;
  }
  mml_png_reader reader{data, static_cast<size_t>(size), 0};
  png_set_read_fn(png, &reader, mml_png_read);
  png_read_info(png, info);
  png_set_expand(png);          // palette/gray/low-depth -> 8-bit
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_gray_to_rgb(png);
  png_set_bgr(png);             // emit BGR directly
  png_read_update_info(png, info);
  const int w = png_get_image_width(png, info);
  const int h = png_get_image_height(png, info);
  const int stride = w * 3;
  buf = static_cast<unsigned char*>(malloc(static_cast<size_t>(stride) * h));
  if (!buf) { png_destroy_read_struct(&png, &info, nullptr); return 1; }
  std::vector<png_bytep> rows(h);
  for (int y = 0; y < h; ++y) rows[y] = buf + static_cast<size_t>(y) * stride;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  *out = buf;
  *width = w;
  *height = h;
  return 0;
}

void mml_free(void* p) { free(p); }

// ---------------------------------------------------------------- batch pool
// Threaded batch decode: the host-side producer feeding device prefetch.
// One call decodes N images in parallel; rows that fail decode get width=0.
struct DecodeJob {
  const unsigned char* data;
  long size;
  unsigned char* out;
  int w, h, ok;
};

int mml_decode_batch(const unsigned char** datas, const long* sizes, int n,
                     unsigned char** outs, int* widths, int* heights,
                     int n_threads) {
  if (n_threads < 1) n_threads = 1;
  std::vector<std::thread> pool;
  std::mutex m;
  int next = 0;
  auto worker = [&]() {
    for (;;) {
      int i;
      { std::lock_guard<std::mutex> g(m); if (next >= n) return; i = next++; }
      unsigned char* out = nullptr;
      int w = 0, h = 0;
      int rc = 1;
      if (sizes[i] >= 8) {
        const unsigned char* d = datas[i];
        if (d[0] == 0xFF && d[1] == 0xD8) {
          rc = mml_decode_jpeg(d, sizes[i], &out, &w, &h);
        } else if (!png_sig_cmp(d, 0, 8)) {
          rc = mml_decode_png(d, sizes[i], &out, &w, &h);
        }
      }
      outs[i] = rc == 0 ? out : nullptr;
      widths[i] = rc == 0 ? w : 0;
      heights[i] = rc == 0 ? h : 0;
    }
  };
  const int k = n_threads < n ? n_threads : n;
  pool.reserve(k);
  for (int t = 0; t < k; ++t) pool.emplace_back(worker);
  for (auto& t : pool) t.join();
  return 0;
}

}  // extern "C"
