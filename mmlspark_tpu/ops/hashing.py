"""Murmur3-based term hashing, bit-compatible with Spark ML's HashingTF.

The reference pins exact hash slot indices in 2^18-dim space
(``core/ml/src/test/scala/HashingTFSpec.scala:22-29``), so the featurizer's
hash function must reproduce Spark's ``Murmur3_x86_32.hashUnsafeBytes`` over
UTF-8 bytes with seed 42, including its quirk of mixing each *trailing* byte
(signed!) as its own 4-byte word, followed by ``Utils.nonNegativeMod``.

The hot path is VECTORIZED: cold terms hash through a numpy batch kernel
(`murmur3_batch`) that processes every term's k-th word in one vector op —
the reference runs its slot scan as a cluster job
(``AssembleFeatures.scala:198-224``); a Python per-token loop would be the
single-box equivalent of forgetting that. Warm terms (repeated vocabulary,
the common case) resolve through a module-level dict at lookup speed.
"""
from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF
SPARK_SEED = 42


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _mix_k1(k1: int) -> int:
    k1 = (k1 * _C1) & _MASK
    k1 = _rotl(k1, 15)
    return (k1 * _C2) & _MASK


def _mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _MASK


def murmur3_x86_32(data: bytes, seed: int = SPARK_SEED) -> int:
    """Spark-compatible murmur3 over bytes; returns a SIGNED 32-bit int."""
    h1 = seed & _MASK
    n_aligned = len(data) - len(data) % 4
    for i in range(0, n_aligned, 4):
        k1 = int.from_bytes(data[i:i + 4], "little")
        h1 = _mix_h1(h1, _mix_k1(k1))
    # Spark tail quirk: each remaining byte is sign-extended and mixed alone.
    for i in range(n_aligned, len(data)):
        b = data[i]
        half_word = b - 256 if b >= 128 else b
        h1 = _mix_h1(h1, _mix_k1(half_word & _MASK))
    h1 ^= len(data)
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _MASK
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _MASK
    h1 ^= h1 >> 16
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


# -- vectorized batch kernel -------------------------------------------------

def _vrotl(x: np.ndarray, n: int) -> np.ndarray:
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _vmix_k1(k1: np.ndarray) -> np.ndarray:
    k1 = k1 * np.uint32(_C1)
    k1 = _vrotl(k1, 15)
    return k1 * np.uint32(_C2)


def _vmix_h1(h1: np.ndarray, k1: np.ndarray) -> np.ndarray:
    h1 = h1 ^ k1
    h1 = _vrotl(h1, 13)
    return h1 * np.uint32(5) + np.uint32(0xE6546B64)


def murmur3_batch(terms: Sequence[str], seed: int = SPARK_SEED) -> np.ndarray:
    """Vectorized murmur3 over a batch of terms (signed int32 per term).

    Terms are grouped into power-of-two length buckets so one long outlier
    (a URL, an un-split blob) can't inflate the padded byte matrix for the
    whole batch; within a bucket padding is bounded at 2x. Each bucket's
    bytes land in one padded uint8 matrix; each 4-byte word position is
    mixed across the bucket in one vector op (per-row validity masked by
    length), then the trailing 1-3 bytes mix sign-extended exactly like the
    scalar path. O(max_term_len_in_bucket) numpy passes per bucket.
    """
    n = len(terms)
    if n == 0:
        return np.zeros(0, np.int32)
    encoded = [t.encode("utf-8") for t in terms]
    lens = np.fromiter((len(b) for b in encoded), np.int64, n)
    buckets = np.zeros(n, np.int64)
    nz = lens > 4
    buckets[nz] = np.ceil(np.log2(lens[nz])).astype(np.int64)
    uniq = np.unique(buckets)
    if len(uniq) == 1:
        return _murmur3_batch_core(encoded, lens, seed)
    out = np.empty(n, np.int32)
    for b in uniq:
        idx = np.nonzero(buckets == b)[0]
        out[idx] = _murmur3_batch_core([encoded[i] for i in idx],
                                       lens[idx], seed)
    return out


def _murmur3_batch_core(encoded: Sequence[bytes], lens: np.ndarray,
                        seed: int) -> np.ndarray:
    n = len(encoded)
    maxlen = int(lens.max())
    with np.errstate(over="ignore"):
        if maxlen == 0:
            h1 = np.full(n, seed, np.uint32)
            return _finalize(h1, lens)
        pad = (maxlen + 3) // 4 * 4
        flat = np.frombuffer(b"".join(encoded), np.uint8)
        starts = np.zeros(n, np.int64)
        np.cumsum(lens[:-1], out=starts[1:])
        buf = np.zeros((n, pad), np.uint8)
        for j in range(maxlen):  # maxlen is small for tokens; row dim is wide
            m = lens > j
            buf[m, j] = flat[starts[m] + j]
        words = (buf[:, 0::4].astype(np.uint32)
                 | (buf[:, 1::4].astype(np.uint32) << np.uint32(8))
                 | (buf[:, 2::4].astype(np.uint32) << np.uint32(16))
                 | (buf[:, 3::4].astype(np.uint32) << np.uint32(24)))
        n_words = lens // 4
        h1 = np.full(n, seed, np.uint32)
        for k in range(pad // 4):
            full = n_words > k
            mixed = _vmix_h1(h1, _vmix_k1(words[:, k]))
            h1 = np.where(full, mixed, h1)
        # tail: each trailing byte sign-extended, mixed alone, in order
        tail_len = lens % 4
        for t in range(3):
            valid = tail_len > t
            if not valid.any():
                break
            idx = np.minimum(n_words * 4 + t, pad - 1)
            b = buf[np.arange(n), idx].astype(np.uint32)
            signed = np.where(b >= 128, b | np.uint32(0xFFFFFF00), b)
            mixed = _vmix_h1(h1, _vmix_k1(signed))
            h1 = np.where(valid, mixed, h1)
        return _finalize(h1, lens)


def _finalize(h1: np.ndarray, lens: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h1 = h1 ^ lens.astype(np.uint32)
        h1 = h1 ^ (h1 >> np.uint32(16))
        h1 = h1 * np.uint32(0x85EBCA6B)
        h1 = h1 ^ (h1 >> np.uint32(13))
        h1 = h1 * np.uint32(0xC2B2AE35)
        h1 = h1 ^ (h1 >> np.uint32(16))
    return h1.view(np.int32)


# term -> signed 32-bit hash; plain dict (read-mostly) beats lru_cache here
_HASH_CACHE: Dict[str, int] = {}
_HASH_CACHE_MAX = 1 << 21


def _term_hash(term: str) -> int:
    h = _HASH_CACHE.get(term)
    if h is None:
        h = murmur3_x86_32(term.encode("utf-8"))
        if len(_HASH_CACHE) < _HASH_CACHE_MAX:
            _HASH_CACHE[term] = h
    return h


def _hashes(terms: Sequence[str]) -> np.ndarray:
    """Signed murmur3 per term: cache hits via dict, misses via the batch
    kernel (one vectorized pass over all cold terms)."""
    cache = _HASH_CACHE
    out = np.empty(len(terms), np.int64)
    miss_i: List[int] = []
    miss_t: List[str] = []
    for i, t in enumerate(terms):
        h = cache.get(t)
        if h is None:
            miss_i.append(i)
            miss_t.append(t)
        else:
            out[i] = h
    if miss_t:
        hs = murmur3_batch(miss_t)
        out[miss_i] = hs
        if len(cache) < _HASH_CACHE_MAX:
            for t, h in zip(miss_t, hs.tolist()):
                cache[t] = h
    return out


def hash_term(term: str, num_features: int) -> int:
    """Slot index for one term: nonNegativeMod(murmur3(term), numFeatures)."""
    if num_features <= 0:
        raise ValueError(f"num_features must be positive, got {num_features}")
    return _term_hash(term) % num_features


def hash_terms(terms: Iterable[str], num_features: int) -> np.ndarray:
    """Slot indices (int64) for a sequence of terms."""
    if num_features <= 0:
        raise ValueError(f"num_features must be positive, got {num_features}")
    terms = terms if isinstance(terms, (list, tuple)) else list(terms)
    # numpy '%' on a negative int64 is already nonNegativeMod
    return _hashes(terms) % num_features


def hash_token_rows(token_rows: Sequence[Sequence[str]],
                    num_features: int) -> Tuple[np.ndarray, np.ndarray]:
    """Flattened slot indices for ragged token rows.

    Returns (slots, row_ptr): ``slots[row_ptr[i]:row_ptr[i+1]]`` are row i's
    slot indices in token order — the CSR layout every downstream scatter
    (TF counts, active-slot scans) consumes without a per-row Python loop.
    """
    n = len(token_rows)
    counts = np.fromiter(
        (len(r) if r is not None else 0 for r in token_rows), np.int64, n)
    row_ptr = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=row_ptr[1:])
    flat: List[str] = []
    for r in token_rows:
        if r:
            flat.extend(r)
    return hash_terms(flat, num_features), row_ptr


def tf_csr(token_rows: Sequence[Sequence[str]], num_features: int
           ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Term-frequency CSR over ragged token rows: (row_ptr, slots, counts).

    Per row, ``slots`` are unique and ascending (Spark SparseVector ordering).
    One np.unique over rowid*num_features+slot keys replaces the reference's
    per-row HashingTF transform loop.
    """
    for r in token_rows:
        if r is None:
            raise ValueError("HashingTF applied to a null token array")
    slots, in_ptr = hash_token_rows(token_rows, num_features)
    n = len(token_rows)
    row_ids = np.repeat(np.arange(n, dtype=np.int64), np.diff(in_ptr))
    keys = row_ids * num_features + slots
    uniq, counts = np.unique(keys, return_counts=True)
    out_rows = uniq // num_features
    out_slots = uniq % num_features
    row_ptr = np.searchsorted(out_rows, np.arange(n + 1, dtype=np.int64))
    return row_ptr, out_slots, counts.astype(np.int64)


def project_slots(fitted: np.ndarray, slots: np.ndarray
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Positions of ``slots`` within the sorted fit-time active-slot array.

    Returns (pos, ok): ``pos[ok]`` are valid compact indices; slots unseen at
    fit have ``ok`` False. THE single definition of the active-slot
    projection used by both HashingTFModel and AssembleFeaturesModel.
    """
    width = len(fitted)
    slots = np.asarray(slots, np.int64)
    if width == 0:
        return np.zeros(len(slots), np.int64), np.zeros(len(slots), bool)
    pos = np.searchsorted(fitted, slots)
    ok = (pos < width) & (fitted[np.minimum(pos, width - 1)] == slots)
    return pos, ok


def term_frequencies(token_rows: Sequence[Sequence[str]],
                     num_features: int) -> List[np.ndarray]:
    """Per-row (slots, counts) pairs — the HashingTF transform per row.

    Returns a list of (k, 2) arrays [slot, count] sorted by slot, mirroring
    Spark's SparseVector ordering so downstream slot selection is stable.
    (Compatibility view over :func:`tf_csr`.)
    """
    row_ptr, slots, counts = tf_csr(token_rows, num_features)
    pairs = np.stack([slots, counts], axis=1)
    return [pairs[row_ptr[i]:row_ptr[i + 1]] for i in range(len(token_rows))]
