"""Murmur3-based term hashing, bit-compatible with Spark ML's HashingTF.

The reference pins exact hash slot indices in 2^18-dim space
(``core/ml/src/test/scala/HashingTFSpec.scala:22-29``), so the featurizer's
hash function must reproduce Spark's ``Murmur3_x86_32.hashUnsafeBytes`` over
UTF-8 bytes with seed 42, including its quirk of mixing each *trailing* byte
(signed!) as its own 4-byte word, followed by ``Utils.nonNegativeMod``.

Hashing is per-term Python with a large LRU cache, so repeated vocabulary
(the common case in tabular/text featurization) hashes at dict-lookup speed;
a C fast path for cold, huge vocabularies belongs to the native runtime layer.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Iterable, List, Sequence

import numpy as np

_C1 = 0xCC9E2D51
_C2 = 0x1B873593
_MASK = 0xFFFFFFFF
SPARK_SEED = 42


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _mix_k1(k1: int) -> int:
    k1 = (k1 * _C1) & _MASK
    k1 = _rotl(k1, 15)
    return (k1 * _C2) & _MASK


def _mix_h1(h1: int, k1: int) -> int:
    h1 ^= k1
    h1 = _rotl(h1, 13)
    return (h1 * 5 + 0xE6546B64) & _MASK


def murmur3_x86_32(data: bytes, seed: int = SPARK_SEED) -> int:
    """Spark-compatible murmur3 over bytes; returns a SIGNED 32-bit int."""
    h1 = seed & _MASK
    n_aligned = len(data) - len(data) % 4
    for i in range(0, n_aligned, 4):
        k1 = int.from_bytes(data[i:i + 4], "little")
        h1 = _mix_h1(h1, _mix_k1(k1))
    # Spark tail quirk: each remaining byte is sign-extended and mixed alone.
    for i in range(n_aligned, len(data)):
        b = data[i]
        half_word = b - 256 if b >= 128 else b
        h1 = _mix_h1(h1, _mix_k1(half_word & _MASK))
    h1 ^= len(data)
    h1 ^= h1 >> 16
    h1 = (h1 * 0x85EBCA6B) & _MASK
    h1 ^= h1 >> 13
    h1 = (h1 * 0xC2B2AE35) & _MASK
    h1 ^= h1 >> 16
    return h1 - (1 << 32) if h1 >= (1 << 31) else h1


@lru_cache(maxsize=1 << 20)
def _term_hash(term: str) -> int:
    return murmur3_x86_32(term.encode("utf-8"))


def hash_term(term: str, num_features: int) -> int:
    """Slot index for one term: nonNegativeMod(murmur3(term), numFeatures)."""
    if num_features <= 0:
        raise ValueError(f"num_features must be positive, got {num_features}")
    return _term_hash(term) % num_features


def hash_terms(terms: Iterable[str], num_features: int) -> np.ndarray:
    """Slot indices (int64) for a sequence of terms."""
    if num_features <= 0:
        raise ValueError(f"num_features must be positive, got {num_features}")
    return np.fromiter((_term_hash(t) % num_features for t in terms),
                       dtype=np.int64)


def term_frequencies(token_rows: Sequence[Sequence[str]],
                     num_features: int) -> List[np.ndarray]:
    """Per-row (slots, counts) pairs — the HashingTF transform per row.

    Returns a list of (k, 2) arrays [slot, count] sorted by slot, mirroring
    Spark's SparseVector ordering so downstream slot selection is stable.
    """
    out = []
    for tokens in token_rows:
        if tokens is None:
            raise ValueError("HashingTF applied to a null token array")
        slots = hash_terms(tokens, num_features)
        uniq, counts = np.unique(slots, return_counts=True)
        out.append(np.stack([uniq, counts.astype(np.int64)], axis=1))
    return out
