"""Fused flash attention as a Pallas TPU kernel.

The L x L score matrix is the HBM killer in long-context attention: plain
``softmax(q @ k^T) @ v`` materializes O(B*H*L^2) floats through HBM three
times (scores, softmax, weighted sum). The flash formulation streams K/V
blocks through VMEM with an online softmax — scores never leave VMEM, HBM
traffic drops to O(B*H*L*D), and both matmuls tile the MXU back to back.

This kernel is the single-device core that composes with the
context-parallel layer (``parallel/sequence.py``): ring attention rotates
K/V blocks BETWEEN chips with the same online-softmax algebra this kernel
applies WITHIN a chip, so `full_attention`'s fallback, this kernel, and
the ring path all agree numerically (tests pin them together).

Layout: (B, L, H, D) like every attention_fn in the framework; the grid
is (B, H, L/block_q), each program owning one query block against the
full K/V stream for its (batch, head).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
BLOCK_Q = 256
BLOCK_K = 256


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float):
    # refs are (1, 1, L-block, D): batch and head ride the grid, so the
    # last two dims are the (8, 128)-tileable (rows, lanes) pair Mosaic
    # wants
    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale        # (bq, d)
    bq = q.shape[0]
    L = k_ref.shape[2]
    d = q.shape[1]
    qi = pl.program_id(2)
    q_idx = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(i, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(i * block_k, block_k), :]
        v = v_ref[0, 0, pl.dslice(i * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)
        if causal:
            k_idx = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(k_idx <= q_idx, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                                # (bq, bk)
        corr = jnp.exp(m - m_new)
        l = l * corr + p.sum(axis=-1, keepdims=True)
        acc = acc * corr + jax.lax.dot(
            p, v.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    # causal: blocks entirely in the masked future contribute nothing —
    # bound the trip count by this program's query block (the dynamic
    # upper bound is supported; saves ~half the matmul work on decoders)
    n_blocks = L // block_k
    if causal:
        n_blocks = jnp.minimum(
            n_blocks, ((qi + 1) * bq + block_k - 1) // block_k)
    _, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    o_ref[0, 0, :, :] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = False, block_q: int = BLOCK_Q,
                    block_k: int = BLOCK_K) -> jax.Array:
    """(B, L, H, D) fused attention; requires L divisible by the blocks
    (``supports`` tells callers when to fall back). Differentiable: the
    backward pass recomputes attention blockwise (``_flash_bwd``), so
    training keeps the O(L * block) memory profile."""
    return _flash_forward(q, k, v, causal, block_q, block_k)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k"))
def _flash_forward(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = False, block_q: int = BLOCK_Q,
                   block_k: int = BLOCK_K) -> jax.Array:
    b, L, h, d = q.shape
    scale = 1.0 / float(np.sqrt(d))
    vmem = pl.ANY if _interpret() else pltpu.VMEM
    kernel = functools.partial(_flash_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    # (B, L, H, D) -> (B, H, L, D): head ahead of length so kernel blocks
    # end in the tileable (rows, lanes) pair; XLA fuses the transposes
    # into the surrounding program
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = pl.pallas_call(
        kernel,
        grid=(b, h, L // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0),
                         memory_space=vmem),
            pl.BlockSpec((1, 1, L, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=vmem),
            pl.BlockSpec((1, 1, L, d), lambda bi, hi, qi: (bi, hi, 0, 0),
                         memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=_interpret(),
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


# per-(batch, head) K/V stay fully VMEM-resident in the kernel; cap their
# footprint well under the ~16 MB of VMEM (f32 worst case, x2 for K and V,
# headroom for q/acc blocks and pipelining buffers)
_VMEM_KV_LIMIT = 1 << 20   # L * d elements


def supports(q_shape, block_q: int = BLOCK_Q, block_k: int = BLOCK_K) -> bool:
    """Whether the fused kernel applies: block-divisible length, a
    lane-friendly head dim, and K/V small enough to stage per (batch,
    head) in VMEM (others fall back to the jnp reference)."""
    _, L, _, d = q_shape
    return L % block_q == 0 and L % block_k == 0 and L >= 2 * block_q \
        and d % 8 == 0 and L * d <= _VMEM_KV_LIMIT


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    out = _flash_forward(q, k, v, causal, block_q, block_k)
    return out, (q, k, v, out)


@functools.partial(jax.jit, static_argnames=("causal", "block_k"))
def _flash_bwd_impl(q, k, v, out, do, causal, block_k):
    """Blockwise flash backward in plain jnp: one ``lax.scan`` over K/V
    blocks recomputes the probabilities from (q, k) plus a recomputed
    row log-sum-exp, and accumulates dq and the per-block dk/dv — memory
    stays O(L * block), never the O(L^2) score matrix, so long-context
    TRAINING keeps the flash memory profile. XLA compiles the scanned
    matmuls straight onto the MXU; no hand-written Mosaic backward
    needed for correctness or memory."""
    b, L, h, d = q.shape
    scale = 1.0 / float(np.sqrt(d))
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    n_idx = jnp.arange(L)

    # pass 1: row log-sum-exp by online max/sum over k blocks
    def lse_body(carry, kb):
        m, s = carry
        kblk, k0 = kb
        logit = jnp.einsum("blhd,bjhd->blhj", qf, kblk,
                           preferred_element_type=jnp.float32)
        if causal:
            mask = (k0 + jnp.arange(block_k))[None, None, None, :] \
                > n_idx[None, :, None, None]
            logit = jnp.where(mask, _NEG_INF, logit)
        m_new = jnp.maximum(m, logit.max(axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(
            logit - m_new[..., None]).sum(axis=-1)
        return (m_new, s), None

    kblocks = kf.reshape(b, L // block_k, block_k, h, d).transpose(
        1, 0, 2, 3, 4)
    vblocks = vf.reshape(b, L // block_k, block_k, h, d).transpose(
        1, 0, 2, 3, 4)
    offsets = jnp.arange(L // block_k) * block_k
    m0 = jnp.full((b, L, h), _NEG_INF, jnp.float32)
    (m, s), _ = jax.lax.scan(lse_body, (m0, jnp.zeros((b, L, h))),
                             (kblocks, offsets))
    lse = m + jnp.log(jnp.maximum(s, 1e-30))

    # D_i = rowsum(do * o) (the softmax-jacobian contraction)
    Drow = (dof * out.astype(jnp.float32)).sum(axis=-1)      # (b, L, h)

    # pass 2: accumulate dq, and per-block dk/dv
    def grad_body(dq, blk):
        kblk, vblk, k0 = blk
        logit = jnp.einsum("blhd,bjhd->blhj", qf, kblk,
                           preferred_element_type=jnp.float32)
        if causal:
            mask = (k0 + jnp.arange(block_k))[None, None, None, :] \
                > n_idx[None, :, None, None]
            logit = jnp.where(mask, _NEG_INF, logit)
        p = jnp.exp(logit - lse[..., None])                  # (b,L,h,bk)
        dp = jnp.einsum("blhd,bjhd->blhj", dof, vblk,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - Drow[..., None])                      # (b,L,h,bk)
        dq = dq + jnp.einsum("blhj,bjhd->blhd", ds, kblk,
                             preferred_element_type=jnp.float32)
        dkb = jnp.einsum("blhj,blhd->bjhd", ds, qf,
                         preferred_element_type=jnp.float32)
        dvb = jnp.einsum("blhj,blhd->bjhd", p, dof,
                         preferred_element_type=jnp.float32)
        return dq, (dkb, dvb)

    dq0 = jnp.zeros((b, L, h, d), jnp.float32)
    dq, (dks, dvs) = jax.lax.scan(grad_body, dq0,
                                  (kblocks, vblocks, offsets))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, L, h, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, L, h, d)
    return ((dq * scale).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


def _flash_bwd_rule(causal, block_q, block_k, res, do):
    q, k, v, out = res
    return _flash_bwd_impl(q, k, v, out, do, causal, block_k)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
