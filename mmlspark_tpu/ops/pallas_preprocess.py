"""Fused uint8 -> normalized-float image preprocessing as a Pallas TPU kernel.

The reference ran resize/crop/normalize per-row through OpenCV JNI on CPUs
(``ImageTransformer.scala``); the BASELINE.json north star asks for this
rewritten as a Pallas kernel fused ahead of the model's first layer.

Why it wins on TPU:
- host->HBM transfer moves uint8 (4x less PCIe/DMA traffic than fp32);
- the uint8->float cast + mean/std normalize runs on the VPU out of VMEM,
  emitting bfloat16 straight into the model's first conv — the fp32 image
  tensor never round-trips through HBM;
- one elementwise pass, batched over the grid, no per-row Python.

Layout note: images are flattened to (B, H*W*C) so the lane dimension is a
multiple of 128 (HWC C=3 alone would waste the VPU lanes); the per-channel
mean/std are pre-tiled host-side into length-N vectors.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _normalize_kernel(u8_ref, mean_ref, inv_std_ref, out_ref):
    # Mosaic has no direct uint8->float cast; hop through int32.
    x = u8_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = ((x - mean_ref[:]) * inv_std_ref[:]).astype(out_ref.dtype)


_BLOCK_B = 8  # sublane tiling requires batch blocks divisible by 8


@functools.partial(jax.jit, static_argnames=("image_shape", "out_dtype"))
def fused_normalize(u8_flat: jax.Array, mean_vec: jax.Array,
                    inv_std_vec: jax.Array,
                    image_shape: Tuple[int, int, int],
                    out_dtype=jnp.bfloat16) -> jax.Array:
    """(B, N) uint8 -> (B, H, W, C) normalized out_dtype; N = H*W*C."""
    b, n = u8_flat.shape
    bp = ((b + _BLOCK_B - 1) // _BLOCK_B) * _BLOCK_B
    if bp != b:
        u8_flat = jnp.pad(u8_flat, ((0, bp - b), (0, 0)))
    vmem = pl.ANY if _interpret() else pltpu.VMEM
    out = pl.pallas_call(
        _normalize_kernel,
        grid=(bp // _BLOCK_B,),
        in_specs=[
            pl.BlockSpec((_BLOCK_B, n), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_BLOCK_B, n), lambda i: (0, 0), memory_space=vmem),
            pl.BlockSpec((_BLOCK_B, n), lambda i: (0, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((_BLOCK_B, n), lambda i: (i, 0),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((bp, n), out_dtype),
        interpret=_interpret(),
    )(u8_flat,
      jnp.broadcast_to(mean_vec[None, :], (_BLOCK_B, n)),
      jnp.broadcast_to(inv_std_vec[None, :], (_BLOCK_B, n)))
    return out[:b].reshape((b,) + tuple(image_shape))


def make_preprocess_fn(image_shape: Tuple[int, int, int],
                       mean: Sequence[float] = (127.5, 127.5, 127.5),
                       std: Sequence[float] = (127.5, 127.5, 127.5),
                       out_dtype=jnp.bfloat16):
    """Returns fn(u8_flat (B, N)) -> (B, H, W, C) normalized activations.

    Compose inside the SAME jit as the model forward so the normalized
    activations feed the first conv without an HBM round trip:

        pre = make_preprocess_fn((32, 32, 3))
        @jax.jit
        def forward(params, u8):
            return module.apply(params, pre(u8))
    """
    h, w, c = image_shape
    n = h * w * c
    mean_vec = jnp.asarray(np.tile(np.asarray(mean, np.float32), h * w))
    inv_std_vec = jnp.asarray(
        np.tile(1.0 / np.asarray(std, np.float32), h * w))
    if mean_vec.shape[0] != n:
        raise ValueError(f"mean length {len(mean)} does not tile into {n}")

    def preprocess(u8_flat: jax.Array) -> jax.Array:
        if u8_flat.dtype != jnp.uint8:
            u8_flat = u8_flat.astype(jnp.uint8)
        return fused_normalize(u8_flat, mean_vec, inv_std_vec,
                               (h, w, c), out_dtype)
    return preprocess


def _sampling_matrix(src: int, dst: int, crop_off: float = 0.0,
                     crop_size: Optional[int] = None) -> np.ndarray:
    """(dst, src) bilinear sampling matrix, half-pixel centers with edge
    clamp — identical convention to the host path (``image/ops.py
    _resize_stack``). An optional crop window folds INTO the matrix: crop
    + resize is just a shifted/scaled sampling grid, so the fused kernel
    gets both for the price of one matmul."""
    size = src if crop_size is None else crop_size
    s = crop_off + (np.arange(dst) + 0.5) * size / dst - 0.5
    i0 = np.clip(np.floor(s).astype(np.int64), 0, src - 1)
    i1 = np.clip(i0 + 1, 0, src - 1)
    frac = np.clip(s - i0, 0.0, 1.0).astype(np.float32)
    m = np.zeros((dst, src), np.float32)
    m[np.arange(dst), i0] += 1.0 - frac
    m[np.arange(dst), i1] += frac
    return m


def _crop_resize_norm_kernel(u8_ref, ry_ref, rxc_ref, mean_ref, istd_ref,
                             out_ref):
    """One image per grid step: cast (VPU) -> H-resize matmul (MXU) ->
    W-resize matmul (MXU) -> requantize + normalize (VPU), all out of
    VMEM. The W-axis matrix is pre-expanded channel-blockwise
    (kron(Rx, I_C)) so both resizes are plain 2-D matmuls — no gathers,
    no transposes, nothing Mosaic has to emulate."""
    # full-f32 matmul precision: default TPU dot rounds operands to bf16,
    # which perturbs resampled pixels by up to +-2 uint8 quanta and breaks
    # parity with the host resize
    x = u8_ref[0].astype(jnp.int32).astype(jnp.float32)      # (Hs, Ws*C)
    y = jax.lax.dot(ry_ref[:], x,
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)       # (Hd, Ws*C)
    z = jax.lax.dot(y, rxc_ref[:],
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)       # (Hd, WdC_pad)
    # re-quantize exactly like the host resize (clip+rint back to uint8
    # range) so fused and host routes score identical images identically
    z = jnp.clip(jnp.round(z), 0.0, 255.0)
    out_ref[0] = ((z - mean_ref[:]) * istd_ref[:]).astype(out_ref.dtype)


def _pad128(n: int) -> int:
    return ((n + 127) // 128) * 128


@functools.partial(jax.jit, static_argnames=("src_hw", "dst_hw", "channels",
                                             "out_dtype"))
def _fused_crop_resize_normalize(u8: jax.Array, ry: jax.Array, rxc: jax.Array,
                                 mean2d: jax.Array, istd2d: jax.Array,
                                 src_hw: Tuple[int, int],
                                 dst_hw: Tuple[int, int], channels: int,
                                 out_dtype=jnp.float32) -> jax.Array:
    b = u8.shape[0]
    hs, ws = src_hw
    hd, wd = dst_hw
    wsc = ws * channels
    wdc_pad = rxc.shape[1]
    vmem = pl.ANY if _interpret() else pltpu.VMEM
    out = pl.pallas_call(
        _crop_resize_norm_kernel,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, hs, wsc), lambda i: (i, 0, 0),
                         memory_space=vmem),
            pl.BlockSpec((hd, hs), lambda i: (0, 0), memory_space=vmem),
            pl.BlockSpec((wsc, wdc_pad), lambda i: (0, 0),
                         memory_space=vmem),
            pl.BlockSpec((hd, wdc_pad), lambda i: (0, 0), memory_space=vmem),
            pl.BlockSpec((hd, wdc_pad), lambda i: (0, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((1, hd, wdc_pad), lambda i: (i, 0, 0),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((b, hd, wdc_pad), out_dtype),
        interpret=_interpret(),
    )(u8, ry, rxc, mean2d, istd2d)
    return out[:, :, :wd * channels].reshape(b, hd, wd, channels)


def make_fused_preprocess_fn(src_shape: Tuple[int, int, int],
                             resize: Optional[Tuple[int, int]] = None,
                             crop: Optional[Tuple[int, int]] = None,
                             mean: Sequence[float] = (0.0,),
                             std: Sequence[float] = (1.0,),
                             out_dtype=jnp.float32):
    """The complete SURVEY §7 preprocess as ONE Pallas kernel: uint8 in,
    center-crop + bilinear-resize + normalize, model-ready activations
    out — the OpenCV pipeline the reference ran per-row on CPUs
    (``ImageTransformer.scala:33-153``), fused ahead of the first layer.

    ``fn(u8 (B, Hs*Ws*C) or (B, Hs, Ws, C)) -> (B, Hd, Wd, C)``.
    ``crop`` is a center-crop (h, w) applied BEFORE ``resize`` (either may
    be None); per-channel ``mean``/``std`` normalize after the host-parity
    requantize. Compose inside the model's jit; pass
    ``out_dtype=jnp.bfloat16`` to feed the first conv in bf16."""
    hs, ws, c = (int(v) for v in src_shape)
    ch, cw = (int(v) for v in crop) if crop else (hs, ws)
    if ch > hs or cw > ws:
        raise ValueError(f"crop {crop} exceeds source {src_shape}")
    hd, wd = (int(v) for v in resize) if resize else (ch, cw)
    # integer floor offsets, matching ops.center_crop's slicing — a
    # fractional offset would blend adjacent pixels instead of cropping
    off_h, off_w = float((hs - ch) // 2), float((ws - cw) // 2)
    ry = _sampling_matrix(hs, hd, off_h, ch)
    rx = _sampling_matrix(ws, wd, off_w, cw)
    wdc_pad = _pad128(wd * c)
    # kron(Rx^T, I_C) with lane padding: column (w*c + k) resamples
    # channel k at output position w
    rxc = np.zeros((ws * c, wdc_pad), np.float32)
    for k in range(c):
        rxc[np.ix_(np.arange(ws) * c + k, np.arange(wd) * c + k)] = rx.T
    mean_row = np.zeros((wdc_pad,), np.float32)
    istd_row = np.zeros((wdc_pad,), np.float32)
    mean_row[:wd * c] = np.tile(np.broadcast_to(
        np.asarray(mean, np.float32), (c,)), wd)
    istd_row[:wd * c] = np.tile(1.0 / np.broadcast_to(
        np.asarray(std, np.float32), (c,)), wd)
    ry_d = jnp.asarray(ry)
    rxc_d = jnp.asarray(rxc)
    mean2d = jnp.asarray(np.broadcast_to(mean_row, (hd, wdc_pad)))
    istd2d = jnp.asarray(np.broadcast_to(istd_row, (hd, wdc_pad)))

    def preprocess(u8: jax.Array) -> jax.Array:
        if u8.dtype != jnp.uint8:
            u8 = u8.astype(jnp.uint8)
        u8 = u8.reshape(u8.shape[0], hs, ws * c)
        return _fused_crop_resize_normalize(
            u8, ry_d, rxc_d, mean2d, istd2d, (hs, ws), (hd, wd), c,
            out_dtype)
    return preprocess


def device_resize_bilinear(x: jax.Array, height: int, width: int) -> jax.Array:
    """On-device bilinear resize of (B, H, W, C) float images, half-pixel
    centers with edge clamp — the SAME convention as the host path
    (``image/ops.py _resize_stack``), so fusing the resize into a scoring
    jit is a pure acceleration, not a semantic change. (``jax.image.resize``
    would anti-alias on downscale and diverge from the OpenCV-style host
    numbers.) Gather indices/weights are compile-time constants; the lerp is
    two taken-row blends per axis, fused by XLA."""
    b, h, w = x.shape[:3]
    if (h, w) == (height, width):
        return x

    def plan(src, dst):
        s = (np.arange(dst) + 0.5) * src / dst - 0.5
        i0 = np.clip(np.floor(s).astype(np.int64), 0, src - 1)
        i1 = np.clip(i0 + 1, 0, src - 1)
        frac = np.clip(s - i0, 0.0, 1.0).astype(np.float32)
        return jnp.asarray(i0), jnp.asarray(i1), jnp.asarray(frac)

    y0, y1, wy = plan(h, height)
    x0, x1, wx = plan(w, width)
    wy = wy[None, :, None, None]
    wx = wx[None, None, :, None]
    r0 = jnp.take(x, y0, axis=1)
    r1 = jnp.take(x, y1, axis=1)
    rows = r0 * (1 - wy) + r1 * wy
    c0 = jnp.take(rows, x0, axis=2)
    c1 = jnp.take(rows, x1, axis=2)
    return c0 * (1 - wx) + c1 * wx
