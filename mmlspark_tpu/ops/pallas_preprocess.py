"""Fused uint8 -> normalized-float image preprocessing as a Pallas TPU kernel.

The reference ran resize/crop/normalize per-row through OpenCV JNI on CPUs
(``ImageTransformer.scala``); the BASELINE.json north star asks for this
rewritten as a Pallas kernel fused ahead of the model's first layer.

Why it wins on TPU:
- host->HBM transfer moves uint8 (4x less PCIe/DMA traffic than fp32);
- the uint8->float cast + mean/std normalize runs on the VPU out of VMEM,
  emitting bfloat16 straight into the model's first conv — the fp32 image
  tensor never round-trips through HBM;
- one elementwise pass, batched over the grid, no per-row Python.

Layout note: images are flattened to (B, H*W*C) so the lane dimension is a
multiple of 128 (HWC C=3 alone would waste the VPU lanes); the per-channel
mean/std are pre-tiled host-side into length-N vectors.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _normalize_kernel(u8_ref, mean_ref, inv_std_ref, out_ref):
    # Mosaic has no direct uint8->float cast; hop through int32.
    x = u8_ref[:].astype(jnp.int32).astype(jnp.float32)
    out_ref[:] = ((x - mean_ref[:]) * inv_std_ref[:]).astype(out_ref.dtype)


_BLOCK_B = 8  # sublane tiling requires batch blocks divisible by 8


@functools.partial(jax.jit, static_argnames=("image_shape", "out_dtype"))
def fused_normalize(u8_flat: jax.Array, mean_vec: jax.Array,
                    inv_std_vec: jax.Array,
                    image_shape: Tuple[int, int, int],
                    out_dtype=jnp.bfloat16) -> jax.Array:
    """(B, N) uint8 -> (B, H, W, C) normalized out_dtype; N = H*W*C."""
    b, n = u8_flat.shape
    bp = ((b + _BLOCK_B - 1) // _BLOCK_B) * _BLOCK_B
    if bp != b:
        u8_flat = jnp.pad(u8_flat, ((0, bp - b), (0, 0)))
    vmem = pl.ANY if _interpret() else pltpu.VMEM
    out = pl.pallas_call(
        _normalize_kernel,
        grid=(bp // _BLOCK_B,),
        in_specs=[
            pl.BlockSpec((_BLOCK_B, n), lambda i: (i, 0), memory_space=vmem),
            pl.BlockSpec((_BLOCK_B, n), lambda i: (0, 0), memory_space=vmem),
            pl.BlockSpec((_BLOCK_B, n), lambda i: (0, 0), memory_space=vmem),
        ],
        out_specs=pl.BlockSpec((_BLOCK_B, n), lambda i: (i, 0),
                               memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((bp, n), out_dtype),
        interpret=_interpret(),
    )(u8_flat,
      jnp.broadcast_to(mean_vec[None, :], (_BLOCK_B, n)),
      jnp.broadcast_to(inv_std_vec[None, :], (_BLOCK_B, n)))
    return out[:b].reshape((b,) + tuple(image_shape))


def make_preprocess_fn(image_shape: Tuple[int, int, int],
                       mean: Sequence[float] = (127.5, 127.5, 127.5),
                       std: Sequence[float] = (127.5, 127.5, 127.5),
                       out_dtype=jnp.bfloat16):
    """Returns fn(u8_flat (B, N)) -> (B, H, W, C) normalized activations.

    Compose inside the SAME jit as the model forward so the normalized
    activations feed the first conv without an HBM round trip:

        pre = make_preprocess_fn((32, 32, 3))
        @jax.jit
        def forward(params, u8):
            return module.apply(params, pre(u8))
    """
    h, w, c = image_shape
    n = h * w * c
    mean_vec = jnp.asarray(np.tile(np.asarray(mean, np.float32), h * w))
    inv_std_vec = jnp.asarray(
        np.tile(1.0 / np.asarray(std, np.float32), h * w))
    if mean_vec.shape[0] != n:
        raise ValueError(f"mean length {len(mean)} does not tile into {n}")

    def preprocess(u8_flat: jax.Array) -> jax.Array:
        if u8_flat.dtype != jnp.uint8:
            u8_flat = u8_flat.astype(jnp.uint8)
        return fused_normalize(u8_flat, mean_vec, inv_std_vec,
                               (h, w, c), out_dtype)
    return preprocess


def device_resize_bilinear(x: jax.Array, height: int, width: int) -> jax.Array:
    """On-device bilinear resize of (B, H, W, C) float images, half-pixel
    centers with edge clamp — the SAME convention as the host path
    (``image/ops.py _resize_stack``), so fusing the resize into a scoring
    jit is a pure acceleration, not a semantic change. (``jax.image.resize``
    would anti-alias on downscale and diverge from the OpenCV-style host
    numbers.) Gather indices/weights are compile-time constants; the lerp is
    two taken-row blends per axis, fused by XLA."""
    b, h, w = x.shape[:3]
    if (h, w) == (height, width):
        return x

    def plan(src, dst):
        s = (np.arange(dst) + 0.5) * src / dst - 0.5
        i0 = np.clip(np.floor(s).astype(np.int64), 0, src - 1)
        i1 = np.clip(i0 + 1, 0, src - 1)
        frac = np.clip(s - i0, 0.0, 1.0).astype(np.float32)
        return jnp.asarray(i0), jnp.asarray(i1), jnp.asarray(frac)

    y0, y1, wy = plan(h, height)
    x0, x1, wx = plan(w, width)
    wy = wy[None, :, None, None]
    wx = wx[None, None, :, None]
    r0 = jnp.take(x, y0, axis=1)
    r1 = jnp.take(x, y1, axis=1)
    rows = r0 * (1 - wy) + r1 * wy
    c0 = jnp.take(rows, x0, axis=2)
    c1 = jnp.take(rows, x1, axis=2)
    return c0 * (1 - wx) + c1 * wx
